"""Mesh / sharding / ring attention tests on the virtual 8-device CPU mesh
(SURVEY.md §4: multi-device logic tested in-process the way the reference
tested master+slave on loopback)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import veles_tpu as vt
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.parallel import (MeshSpec, blockwise_attention, fsdp_rules,
                                make_mesh, ring_attention,
                                tensor_parallel_rules)
from veles_tpu.parallel.ring_attention import full_attention
from veles_tpu.units import (All2AllSoftmax, All2AllTanh, EvaluatorSoftmax,
                             Workflow)


def _fc_wf():
    wf = Workflow("fc")
    wf.add(All2AllTanh(32, name="fc1"))
    wf.add(All2AllSoftmax(4, name="out", inputs=("fc1",)))
    wf.add(EvaluatorSoftmax(name="ev", inputs=("out", "@labels", "@mask")))
    return wf


def _blob_loader(rng, n=512, mb=64):
    centers = np.random.default_rng(7).standard_normal((4, 16)) * 3
    lab = rng.integers(0, 4, n).astype(np.int32)
    d = (centers[lab] + rng.standard_normal((n, 16))).astype(np.float32)
    return vt.ArrayLoader({TRAIN: d, VALID: d[:128]},
                          {TRAIN: lab, VALID: lab[:128]}, minibatch_size=mb)


def test_mesh_spec_tiling():
    assert len(jax.devices()) == 8
    m = make_mesh()
    assert m.shape == {"data": 8, "fsdp": 1, "model": 1, "seq": 1,
                       "pipe": 1, "expert": 1}
    m2 = make_mesh(MeshSpec(data=-1, model=2))
    assert m2.shape["data"] == 4 and m2.shape["model"] == 2
    with pytest.raises(ValueError, match="does not tile"):
        make_mesh(MeshSpec(data=3, model=2))


def test_data_parallel_training_matches_single_device(rng):
    """DP over 8 devices must be numerically equivalent to one device —
    the correctness bar for replacing the reference's master-slave
    aggregation with GSPMD psum."""
    wf1, wf2 = _fc_wf(), _fc_wf()
    l1 = _blob_loader(np.random.default_rng(3))
    l2 = _blob_loader(np.random.default_rng(3))

    t1 = vt.Trainer(wf1, l1, vt.optimizers.SGD(0.05, momentum=0.9),
                    vt.Decision(max_epochs=2))
    t1.initialize(seed=0)
    t1.run()

    mesh = make_mesh()
    t2 = vt.Trainer(wf2, l2, vt.optimizers.SGD(0.05, momentum=0.9),
                    vt.Decision(max_epochs=2), mesh=mesh)
    t2.initialize(seed=0)
    t2.run()

    w1 = np.asarray(t1.wstate["params"]["fc1"]["w"])
    w2 = np.asarray(t2.wstate["params"]["fc1"]["w"])
    np.testing.assert_allclose(w1, w2, rtol=1e-4, atol=1e-5)
    assert t2.decision.best_value == pytest.approx(
        t1.decision.best_value, abs=0.5)


def test_fsdp_rule_shards_large_params():
    mesh = make_mesh(MeshSpec(data=2, fsdp=4))
    wf = _fc_wf()
    from veles_tpu.units import Spec
    wf.build({"@input": Spec((64, 16), jnp.float32),
              "@labels": Spec((64,), jnp.int32),
              "@mask": Spec((64,), jnp.float32)})
    opt = vt.optimizers.SGD(0.1, momentum=0.9)
    ws = wf.init_state(jax.random.key(0), opt)
    from veles_tpu.parallel.mesh import state_shardings
    sh = state_shardings(ws, mesh, fsdp_rules(min_size=128))
    # fc1/w is 16x32=512 >= 128 -> sharded over fsdp on its largest dim (32)
    assert sh["params"]["fc1"]["w"].spec == P(None, "fsdp")
    # bias 32 < 128 -> replicated
    assert sh["params"]["fc1"]["b"].spec == P()
    # placement works and training still runs
    step, state_sh, batch_sh = wf.make_sharded_train_step(
        opt, mesh, ws, {"@input": Spec((64, 16), jnp.float32),
                        "@labels": Spec((64,), jnp.int32),
                        "@mask": Spec((64,), jnp.float32)},
        rule=fsdp_rules(min_size=128))
    ws = jax.device_put(ws, state_sh)
    batch = {"@input": jnp.ones((64, 16)),
             "@labels": jnp.zeros((64,), jnp.int32),
             "@mask": jnp.ones((64,))}
    ws2, mets = step(ws, batch)
    assert "loss" in mets


def test_tensor_parallel_rules_table():
    mesh = make_mesh(MeshSpec(data=4, model=2))
    rule = tensor_parallel_rules({"fc1/w": P(None, "model"),
                                  "out/w": P("model", None)})
    spec = jax.ShapeDtypeStruct((16, 32), jnp.float32)
    assert rule(("params", "fc1", "w"), spec) == P(None, "model")
    assert rule(("params", "other", "w"), spec) == P()


def test_blockwise_attention_matches_full(rng):
    B, T, H, D = 2, 64, 4, 8
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T, H, D)).astype(np.float32)
    ref = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    got = blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), block_size=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # causal + non-divisible block size
    ref_c = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=True)
    got_c = blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), block_size=24, causal=True)
    np.testing.assert_allclose(np.asarray(got_c), np.asarray(ref_c),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(rng, causal):
    """Sequence-parallel ring attention over 8 devices == full attention."""
    mesh = make_mesh(MeshSpec(data=1, seq=8))
    B, T, H, D = 2, 128, 2, 8
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T, H, D)).astype(np.float32)
    ref = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         causal=causal)
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_fsdp_axis_size_divisibility():
    rule = fsdp_rules(min_size=16, axis_size=4)
    spec = jax.ShapeDtypeStruct((50, 48), jnp.float32)
    # dim0=50 not divisible by 4 -> falls through to dim1=48
    assert rule((), spec) == P(None, "fsdp")
    spec2 = jax.ShapeDtypeStruct((50, 49), jnp.float32)
    assert rule((), spec2) == P()


def test_sharded_rollback_keeps_mesh(rng):
    """Rollback under a mesh must recompile sharded, not collapse to
    single-device (review regression)."""
    mesh = make_mesh()
    loader = _blob_loader(np.random.default_rng(5), n=256, mb=32)
    wf = _fc_wf()
    dec = vt.Decision(max_epochs=4, fail_iterations=10, rollback_after=1)
    tr = vt.Trainer(wf, loader, vt.optimizers.SGD(0.05, momentum=0.9), dec,
                    mesh=mesh)
    tr.initialize(seed=0)
    tr.run()
    # state still placed with the mesh sharding
    sh = tr.wstate["params"]["fc1"]["w"].sharding
    assert getattr(sh, "mesh", None) is not None
    assert tr._state_sh is not None


def test_ring_attention_sliding_window(rng):
    """Sequence-parallel sliding-window attention matches the dense
    windowed reference on global positions."""
    from veles_tpu.parallel import MeshSpec, make_mesh, ring_attention
    T, window = 64, 24
    mesh = make_mesh(MeshSpec(seq=4))
    q, k, v = (jnp.asarray(rng.standard_normal((1, T, 2, 8)), jnp.float32)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh, causal=True, window=window)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (8 ** -0.5)
    qp = jnp.arange(T)[:, None]
    kp = jnp.arange(T)[None, :]
    m = (kp <= qp) & (kp > qp - window)
    ref = jnp.einsum("bhqk,bkhd->bqhd",
                     jax.nn.softmax(jnp.where(m[None, None], s, -jnp.inf),
                                    axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_window_zero_rejected(rng):
    from veles_tpu.parallel import MeshSpec, make_mesh, ring_attention
    from veles_tpu.parallel.ring_attention import blockwise_attention
    q = jnp.ones((1, 32, 1, 8))
    with pytest.raises(ValueError):
        blockwise_attention(q, q, q, causal=True, window=0)
    mesh = make_mesh(MeshSpec(seq=4))
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh, causal=True, window=0)


def test_ring_attention_gqa(rng):
    """Ring attention with grouped kv heads: ring traffic stays kv-sized,
    numerics equal the repeated-head dense reference."""
    from veles_tpu.parallel import MeshSpec, make_mesh, ring_attention
    from veles_tpu.parallel.ring_attention import full_attention
    T, Hk, G = 32, 2, 2
    mesh = make_mesh(MeshSpec(seq=4))
    q = jnp.asarray(rng.standard_normal((1, T, Hk * G, 8)), jnp.float32)
    k, v = (jnp.asarray(rng.standard_normal((1, T, Hk, 8)), jnp.float32)
            for _ in range(2))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = full_attention(q, jnp.repeat(k, G, 2), jnp.repeat(v, G, 2),
                         causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
