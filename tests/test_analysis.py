"""veles_tpu.analysis — the trace-discipline / host-concurrency /
config-drift static analyzer (docs/analysis.md).

Fixture snippets per rule family (positive + negative + suppression),
baseline semantics, the CLI contract, and — the CI gate itself — a
self-check that the live package holds ZERO unbaselined findings, run
pure-AST without importing any jax-heavy module.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from veles_tpu.analysis import (analyze_files, iter_python_files,
                                run_analysis)
from veles_tpu.analysis.baseline import write_baseline
from veles_tpu.analysis.cli import main as lint_main
from veles_tpu.analysis.pysrc import parse_file
from veles_tpu.analysis.registry import TRACE_ROOTS

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def _lint(tmp_path, **kw):
    return analyze_files(iter_python_files([str(tmp_path)]), **kw)


def _rules(findings):
    return sorted(f.rule for f in findings)


# -- VT1xx: trace safety ----------------------------------------------------

def test_vt101_tracer_branch_flagged(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT101"]
    assert "y > 0" in found[0].message
    assert found[0].symbol == "step"


def test_vt101_static_branches_not_flagged(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x, pages=None, *, greedy=True):  # trace-root: traced
            if pages is not None:      # None-check: static structure
                x = x + 1
            if greedy:                 # keyword-only knob: static
                return jnp.max(x)
            if x.ndim == 2:            # array metadata: static
                return x
            return jnp.sum(x)
        """)
    assert _lint(tmp_path) == []


def test_vt101_builder_params_are_static(tmp_path):
    # builder mode: the factory's own params are plans/config, not
    # tracers — but its nested def IS the traced program
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def make_step(page_size):  # trace-root: builder
            if page_size is None:
                page_size = 16

            def step(x):
                if jnp.sum(x) > 0:
                    return x
                return -x
            return step
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT101"]
    assert found[0].symbol == "make_step.step"


def test_vt102_host_coercions(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp
        import numpy as np

        def step(x):  # trace-root: traced
            a = float(jnp.sum(x))
            b = np.asarray(x * 2)
            c = x.sum().item()
            return a, b, c
        """)
    assert _rules(_lint(tmp_path)) == ["VT102", "VT102", "VT102"]


def test_vt103_host_effects_only_inside_traced_scope(tmp_path):
    _write(tmp_path, "mod.py", """\
        import random
        import time

        def step(x):  # trace-root: traced
            t = time.monotonic()
            r = random.random()
            return x + t + r

        def host_helper():
            return time.monotonic()    # not traced scope: fine
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT103", "VT103"]
    assert all(f.symbol == "step" for f in found)


def test_vt104_unordered_iteration(tmp_path):
    _write(tmp_path, "mod.py", """\
        def step(x):  # trace-root: traced
            acc = 0
            for k in {"b", "a"}:
                acc = acc + x
            for k in sorted({"b", "a"}):   # deterministic: fine
                acc = acc + x
            return acc
        """)
    assert _rules(_lint(tmp_path)) == ["VT104"]


def test_traced_scope_closes_over_local_calls(tmp_path):
    # a helper the traced root calls joins traced scope module-locally
    _write(tmp_path, "mod.py", """\
        import time

        def helper(n):
            return time.sleep(n)

        def step(x):  # trace-root: traced
            helper(1)
            return x
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT103"]
    assert found[0].symbol == "helper"


# -- suppressions -----------------------------------------------------------

def test_suppression_with_reason(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            # lint: disable=VT101 trace-time structural check, honest
            if y > 0:
                return y
            return -y
        """)
    assert _lint(tmp_path) == []


def test_suppression_without_reason_is_va001(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:  # lint: disable=VT101
                return y
            return -y
        """)
    found = _lint(tmp_path)
    # the finding is suppressed, but the missing justification is
    # itself a finding
    assert _rules(found) == ["VA001"]


def test_suppression_only_covers_named_rule(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:  # lint: disable=VT104 wrong rule named
                return y
            return -y
        """)
    assert _rules(_lint(tmp_path)) == ["VT101"]


# -- VC2xx: concurrency discipline ------------------------------------------

def test_vc201_guarded_field(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock

            def good(self, x):
                with self._lock:
                    self._items.append(x)

            def helper(self):  # requires-lock: self._lock
                return list(self._items)

            def bad(self):
                return len(self._items)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "Box.bad"


def test_vc201_requires_lock_call_sites_checked(tmp_path):
    # annotating a method `# requires-lock:` moves the obligation to
    # its callers — it must not silently erase lock checking
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def _bump(self):  # requires-lock: self._lock
                self._n += 1

            def good(self):
                with self._lock:
                    self._bump()

            def bad(self):
                self._bump()
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "Box.bad" and "_bump" in found[0].message


def test_vc201_not_shared_exemption(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock
                self._setup()

            def _setup(self):  # not-shared: called from __init__ only
                self._items.append(0)
        """)
    assert _lint(tmp_path) == []


def test_vc201_module_global(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        _lock = threading.Lock()
        _seen = set()  # guarded-by: _lock

        def good(k):
            with _lock:
                _seen.add(k)

        def bad(k):
            return k in _seen
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "bad"


def test_vc202_bare_acquire(tmp_path):
    _write(tmp_path, "mod.py", """\
        def risky(lock):
            lock.acquire()
            lock.release()

        def safe(lock):
            lock.acquire()
            try:
                pass
            finally:
                lock.release()
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC202"]
    assert found[0].symbol == "risky"


def test_vc203_unknown_lock_name(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lokc
        """)
    assert "VC203" in _rules(_lint(tmp_path))


# -- VK3xx: config drift ----------------------------------------------------

def _config_fixture(tmp_path):
    _write(tmp_path, "config.py", """\
        class _C:  # stand-in tree; the rule is pure AST
            pass

        root = _C()

        def _defaults():
            root.common.alpha = 1
            root.common.beta = 2
            root.common.serve.gamma = 3
        """)
    _write(tmp_path, "user.py", """\
        from config import root

        val = root.common.alpha
        missing = root.common.get("nope", 1)
        serve = root.common.serve
        g = serve.get("gamma", 3)
        """)


def test_vk301_undeclared_read(tmp_path):
    _config_fixture(tmp_path)
    found = [f for f in _lint(tmp_path) if f.rule == "VK301"]
    assert len(found) == 1
    assert "root.common.nope" in found[0].message
    assert found[0].path.endswith("user.py")


def test_vk302_dead_declaration(tmp_path):
    _config_fixture(tmp_path)
    dead = [f for f in _lint(tmp_path) if f.rule == "VK302"]
    assert ["root.common.beta" in f.message for f in dead] == [True]
    assert dead[0].path.endswith("config.py")


def test_vk303_undocumented_key(tmp_path):
    _config_fixture(tmp_path)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "configuration.md").write_text(
        "`root.common.alpha` and `root.common.serve.gamma` exist\n")
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VK303"]
    assert len(found) == 1 and "root.common.beta" in found[0].message


def test_vk_alias_get_counts_as_read(tmp_path):
    # serve = root.common.serve; serve.get("gamma") must NOT leave
    # gamma "dead" (the engine/deploy idiom)
    _config_fixture(tmp_path)
    assert not any("gamma" in f.message for f in _lint(tmp_path)
                   if f.rule == "VK302")


# -- VM4xx: metric-name drift ----------------------------------------------

def _metrics_fixture(tmp_path):
    # the __init__.py makes this a package-directory scan — the shape
    # VM402 requires (a subset scan cannot prove "registered nowhere")
    _write(tmp_path, "__init__.py", "")
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.counter("vt_good_total", "documented")
            reg.histogram("vt_lat_seconds", "documented histogram")
            reg.gauge("vt_undocumented_gauge", "nobody wrote me up")
            reg.counter("plain_counter", "not in the vt_ namespace")
        """)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `vt_good_total` | counter |\n"
        "`vt_lat_seconds` (derived: `vt_lat_seconds_bucket`,\n"
        "`vt_lat_seconds_sum`, `vt_lat_seconds_count`)\n"
        "| `vt_ghost_total` | counter | documented, never registered |\n")
    return docs


def test_vm401_registered_but_undocumented(tmp_path):
    docs = _metrics_fixture(tmp_path)
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VM401"]
    assert len(found) == 1
    assert "vt_undocumented_gauge" in found[0].message
    assert found[0].path.endswith("mod.py")
    assert found[0].severity == "error"


def test_vm402_documented_but_unregistered(tmp_path):
    docs = _metrics_fixture(tmp_path)
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VM402"]
    # vt_ghost_total fires; the derived _bucket/_sum/_count series of
    # the registered histogram are exempt
    assert len(found) == 1
    assert "vt_ghost_total" in found[0].message


def test_vm402_skipped_on_subset_scans(tmp_path):
    """Linting one file (no package __init__.py in the scan) must not
    flag every metric registered in UNSCANNED modules as 'registered
    nowhere' — VM401 still fires per-file, VM402 needs the package."""
    docs = _metrics_fixture(tmp_path)
    mod = str(tmp_path / "mod.py")
    found = analyze_files(iter_python_files([mod]),
                          docs_dir=str(docs))
    rules = _rules(found)
    assert "VM402" not in rules          # subset scan: no VM402
    assert "VM401" in rules              # per-file check still on


def test_vm4xx_covers_perf_observability_names(tmp_path):
    """The deep-performance metric family (memory ledger, goodput/MFU,
    SLO burn, profiler) rides the same VM4xx contract as the serving
    metrics: registered+documented names pass, an undocumented
    registration of one fires VM401, a documented ghost fires VM402."""
    _write(tmp_path, "__init__.py", "")
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.gauge("vt_hbm_bytes_in_use", "documented")
            reg.gauge("vt_train_mfu", "documented")
            reg.gauge("vt_decode_mbu", "documented")
            reg.gauge("vt_slo_burn_rate", "documented",
                      labels=("slo",))
            reg.counter("vt_profile_captures_total", "documented")
            reg.gauge("vt_memory_headroom_slots", "nobody wrote me up")
        """)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `vt_hbm_bytes_in_use` | gauge |\n"
        "| `vt_train_mfu` | gauge |\n"
        "| `vt_decode_mbu` | gauge |\n"
        "| `vt_slo_burn_rate` | gauge |\n"
        "| `vt_profile_captures_total` | counter |\n"
        "| `vt_hbm_bytes_limit` | gauge | documented, registered "
        "nowhere in this fixture |\n")
    found = _lint(tmp_path, docs_dir=str(docs))
    vm401 = [f for f in found if f.rule == "VM401"]
    vm402 = [f for f in found if f.rule == "VM402"]
    assert len(vm401) == 1
    assert "vt_memory_headroom_slots" in vm401[0].message
    assert len(vm402) == 1
    assert "vt_hbm_bytes_limit" in vm402[0].message


def test_perf_observability_modules_stay_host_side():
    """Guard: the memory poller / SLO ring / profiler layer is host
    code — no trace roots are declared in those modules, the analyzer
    finds nothing in them, and the engine's traced program builders
    never reference the observability layer (a thread or time.sleep
    leaking into a compiled program would be a silent perf bug the
    flat compile counters can't see)."""
    import ast
    for mod in ("runtime/memory.py", "runtime/slo.py",
                "runtime/profiler.py"):
        assert not TRACE_ROOTS.get(mod), mod
        path = os.path.join(REPO, "veles_tpu", mod)
        assert not analyze_files(iter_python_files([path])), mod
    # the traced-scope builders in engine/generate must not pull the
    # host observability layer into program scope
    banned = re.compile(
        r"\b(memory_monitor|slo_tracker|profiler|tree_bytes"
        r"|HistogramWindow)\b")
    for mod, roots in TRACE_ROOTS.items():
        if not roots:
            continue
        path = os.path.join(REPO, "veles_tpu", mod)
        tree = ast.parse(open(path).read())
        wanted = set()
        for q in roots:
            wanted.add(q.split(".")[-1])
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in wanted:
                src = ast.get_source_segment(open(path).read(), node)
                assert not banned.search(src or ""), (mod, node.name)


def test_vm4xx_noop_without_observability_md(tmp_path):
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.counter("vt_orphan_total", "no docs tree at all")
        """)
    assert not [f for f in _lint(tmp_path) if f.rule.startswith("VM")]
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "other.md").write_text("no observability file here\n")
    assert not [f for f in _lint(tmp_path, docs_dir=str(docs))
                if f.rule.startswith("VM")]


# -- baseline ---------------------------------------------------------------

def test_baseline_accepts_then_goes_stale_on_edit(tmp_path):
    mod = _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    bp = str(tmp_path / "baseline.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r1["findings"]) == ["VT101"]

    write_baseline(bp, r1["all"])
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert r2["findings"] == [] and _rules(r2["accepted"]) == ["VT101"]

    # editing the flagged line invalidates its fingerprint on purpose —
    # and the orphaned baseline entry itself surfaces as VA002 so the
    # debt record cannot silently linger
    mod.write_text(mod.read_text().replace("if y > 0:", "if y > 1:"))
    r3 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r3["findings"]) == ["VA002", "VT101"]


def test_va003_never_baselined(tmp_path):
    # a file that does not parse was never analyzed: no baseline may
    # green it (its fingerprint has no symbol/snippet to go stale on)
    _write(tmp_path, "broken.py", "def oops(:\n")
    bp = str(tmp_path / "bl.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r1["findings"]) == ["VA003"]
    write_baseline(bp, r1["all"])
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r2["findings"]) == ["VA003"]     # still new


def test_config_alias_poisoned_by_unrelated_local(tmp_path):
    # `serve = {...}` in another function must not make its .get()
    # calls look like config reads (file-wide alias disqualification)
    _write(tmp_path, "config.py", """\
        root = None

        def _defaults():
            root.common.alpha = 1
        """)
    _write(tmp_path, "other.py", """\
        from config import root

        def a():
            serve = root.common.alpha
            return serve

        def b():
            serve = {"meta": 1}
            return serve.get("meta")
        """)
    assert not [f for f in _lint(tmp_path) if f.rule == "VK301"]


# -- VS5xx: sharding / collective discipline --------------------------------

def _mesh_fixture(tmp_path):
    """Declares axes {data, model, seq} the way parallel/mesh.py does
    (MeshSpec dataclass fields — pure AST, nothing imported)."""
    _write(tmp_path, "mesh.py", """\
        from dataclasses import dataclass

        @dataclass
        class MeshSpec:
            data: int = -1
            model: int = 1
            seq: int = 1
        """)


def test_vs501_undeclared_psum_axis(tmp_path):
    """Acceptance seed: an undeclared psum axis produces exactly ONE
    finding with the right rule id and file:line."""
    _mesh_fixture(tmp_path)
    _write(tmp_path, "coll.py", """\
        import jax

        def body(x):  # shard-map-root: data
            return jax.lax.psum(x, "tensor")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS501"]
    f = found[0]
    assert f.path.endswith("coll.py") and f.line == 4
    assert "tensor" in f.message and f.symbol == "body"


def test_vs501_axis_outside_scope_environment(tmp_path):
    # 'model' IS declared on the mesh, but this shard_map scope binds
    # only 'data' — still VS501 (the env-mismatch variant)
    _mesh_fixture(tmp_path)
    _write(tmp_path, "coll.py", """\
        import jax

        def body(x):  # shard-map-root: data
            return jax.lax.psum(x, "model")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS501"]
    assert "does not bind" in found[0].message


def test_vs501_declared_axis_clean_and_suppressible(tmp_path):
    _mesh_fixture(tmp_path)
    _write(tmp_path, "coll.py", """\
        import jax

        def body(x):  # shard-map-root: data
            return jax.lax.psum(x, "data")

        def odd(x):  # shard-map-root: data
            # lint: disable=VS501 axis injected by the test harness
            return jax.lax.psum(x, "bogus")
        """)
    assert _lint(tmp_path) == []


def test_vs502_collective_outside_shard_map_scope(tmp_path):
    _mesh_fixture(tmp_path)
    _write(tmp_path, "coll.py", """\
        import jax

        def stray(x):
            return jax.lax.psum(x, "data")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS502"]
    assert found[0].symbol == "stray"


def test_vs502_closure_covers_called_helpers(tmp_path):
    # a helper the shard-map root calls joins the scope module-locally
    _mesh_fixture(tmp_path)
    _write(tmp_path, "coll.py", """\
        import jax

        def helper(x):
            return jax.lax.ppermute(x, "data", [(0, 1)])

        def body(x):  # shard-map-root: data
            return helper(x)
        """)
    assert _lint(tmp_path) == []


def test_vs503_partition_spec_undeclared_axis(tmp_path):
    _mesh_fixture(tmp_path)
    _write(tmp_path, "specs.py", """\
        from jax.sharding import PartitionSpec as P

        GOOD = P("data", None)
        BAD = P(None, "tensor")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS503"]
    assert "tensor" in found[0].message and found[0].line == 4


def test_vs5xx_silent_without_mesh_declarations(tmp_path):
    # a subset scan that can see no mesh cannot prove "undeclared":
    # VS501/VS503 bail (VS502 is scope-only and still fires)
    _write(tmp_path, "specs.py", """\
        from jax.sharding import PartitionSpec as P

        BAD = P("whatever",)
        """)
    assert _lint(tmp_path) == []


def test_vs5xx_live_registry_roots_resolve():
    """Every SHARD_MAP_ROOTS qualname resolves in its module, so
    renames can't silently drop collective coverage (the VS twin of
    test_registry_roots_exist)."""
    from veles_tpu.analysis.registry import SHARD_MAP_ROOTS
    pkg = os.path.join(REPO, "veles_tpu")
    for relmod, roots in SHARD_MAP_ROOTS.items():
        path = os.path.join(pkg, relmod)
        assert os.path.isfile(path), relmod
        pf = parse_file(path, relmod)
        for q, env in roots.items():
            assert q in pf.functions, (relmod, q)
            assert env and all(isinstance(a, str) for a in env)


# -- VP6xx: recompile hazards ------------------------------------------------

def test_vp601_len_into_builder_slot(tmp_path):
    """Acceptance seed: a len(queue) fed to a static builder slot
    produces exactly ONE finding with the right rule id and
    file:line."""
    _write(tmp_path, "mod.py", """\
        def make_step(n):  # trace-root: builder
            def step(x):
                return x * n
            return step

        def host(queue):
            return make_step(len(queue))
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP601"]
    f = found[0]
    assert f.path.endswith("mod.py") and f.line == 7
    assert "len()" in f.message and f.symbol == "host"


def test_vp601_loop_variable_and_negatives(tmp_path):
    _write(tmp_path, "mod.py", """\
        def make_step(n):  # trace-root: builder
            def step(x):
                return x * n
            return step

        def warm(sizes):
            fns = []
            for n in sizes:
                fns.append(make_step(n))
            return fns

        def fine():
            return make_step(4)

        def justified(sizes):
            for n in (8, 16):
                # lint: disable=VP601 two static buckets by design
                make_step(n)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP601"]
    assert found[0].symbol == "warm" and "loop variable" in found[0].message


def test_vp601_skips_builder_internal_composition(tmp_path):
    # builders composing sub-builders at build time (loops over static
    # model structure) are inside ONE program build, not a recompile
    # stream — the engine/generate idiom
    _write(tmp_path, "mod.py", """\
        def make_cache(u):  # trace-root: builder
            return {"u": u}

        def make_all(units):  # trace-root: builder
            out = {}
            for i, u in enumerate(units):
                out[str(i)] = make_cache(u)
            return out
        """)
    assert _lint(tmp_path) == []


def test_vp602_mapping_order_structure(tmp_path):
    _write(tmp_path, "mod.py", """\
        def make_tree(cfgs):  # trace-root: builder
            return {k: v * 2 for k, v in cfgs.items()}

        def make_tree_sorted(cfgs):  # trace-root: builder
            return {k: v * 2 for k, v in sorted(cfgs.items())}
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP602"]
    assert found[0].symbol == "make_tree" and "cfgs" in found[0].message
    assert found[0].severity == "warning"


def test_vp602_ignores_nested_traced_defs(tmp_path):
    # dict iteration inside the builder's NESTED def is the traced
    # program's data plumbing (plan.step work dicts), not build-time
    # structure construction
    _write(tmp_path, "mod.py", """\
        def make_step(cfgs):  # trace-root: builder
            def step(caches):
                return {k: v for k, v in caches.items()}
            return step
        """)
    assert _lint(tmp_path) == []


def test_vp603_builder_on_hot_path_outside_step_cache(tmp_path):
    _write(tmp_path, "mod.py", """\
        def make_fn(plan):  # trace-root: builder
            def fn(x):
                return x
            return fn

        def handler(plan):  # host-loop-root:
            return make_fn(plan)

        def good(plan, cache):  # host-loop-root:
            step, _, _ = cache.get_step(
                "k", (), lambda: (make_fn(plan), None, None), ())
            return step
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP603"]
    assert found[0].symbol == "handler" and "make_fn" in found[0].message


def test_vp603_self_caching_builders_declared_honestly():
    """generate/generate_beam are exempted from VP603 because they own
    a per-geometry memo — this guard fails if the memo disappears while
    the registry still claims it (the declaration must stay honest)."""
    from veles_tpu.analysis.registry import SELF_CACHING_BUILDERS
    src = open(os.path.join(REPO, "veles_tpu", "runtime",
                            "generate.py")).read()
    for name in SELF_CACHING_BUILDERS:
        assert f"def {name}(" in src, name
    assert "_runner_cache" in src


def test_vp6xx_skips_test_files(tmp_path):
    # tests loop builders over geometries on purpose
    _write(tmp_path, "test_mod.py", """\
        def make_step(n):  # trace-root: builder
            return n

        def test_warm(sizes):
            return [make_step(n) for n in sizes]
        """)
    assert _lint(tmp_path) == []


def test_vp6xx_host_loop_registry_roots_resolve():
    from veles_tpu.analysis.registry import HOST_LOOP_ROOTS
    pkg = os.path.join(REPO, "veles_tpu")
    for relmod, roots in HOST_LOOP_ROOTS.items():
        path = os.path.join(pkg, relmod)
        assert os.path.isfile(path), relmod
        pf = parse_file(path, relmod)
        for q in roots:
            assert q in pf.functions, (relmod, q)


# -- VC204/VC205: the interprocedural lock graph -----------------------------

def test_vc204_lock_order_inversion(tmp_path):
    """Acceptance seed: a lock-order inversion produces exactly ONE
    finding with the right rule id and file:line."""
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC204"]
    f = found[0]
    assert f.path.endswith("mod.py") and f.line == 10
    assert "_a" in f.message and "_b" in f.message


def test_vc204_interprocedural_through_calls(tmp_path):
    # the B-acquisition hides behind a method call; the module-local
    # closure still sees the edge
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._grab_b()

            def _grab_b(self):
                with self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert _rules(_lint(tmp_path)) == ["VC204"]


def test_vc204_consistent_order_is_clean(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert _lint(tmp_path) == []


def test_vc204_reentrant_self_acquire_is_clean(tmp_path):
    # the RLock pattern the deploy control plane uses on purpose
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._a = threading.RLock()

            def outer(self):
                with self._a:
                    self.inner()

            def inner(self):
                with self._a:
                    pass
        """)
    assert _lint(tmp_path) == []


def test_vc205_blocking_under_annotated_lock(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # guarded-by: self._lock

            def bad(self):
                with self._lock:
                    time.sleep(1)
                    self._q.append(1)

            def good(self):
                time.sleep(1)
                with self._lock:
                    self._q.append(1)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC205"]
    assert found[0].symbol == "Box.bad"
    assert "time.sleep" in found[0].message


def test_vc205_io_through_helper_call(tmp_path):
    # the StatusReporter shape this rule was built to catch: file IO
    # reached THROUGH a helper while the data lock is held
    _write(tmp_path, "mod.py", """\
        import threading

        class Rep:
            def __init__(self):
                self._lock = threading.Lock()
                self._doc = {}  # guarded-by: self._lock

            def flush(self):
                with self._lock:
                    self._write()

            def _write(self):
                with open("f", "w") as f:
                    f.write(str(self._doc))
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VC205"]
    assert len(found) == 1
    assert found[0].symbol == "Rep.flush" and "_write" in found[0].message


def test_vc205_unannotated_io_mutex_is_clean(tmp_path):
    # a dedicated IO-serialization mutex (no guarded-by fields) may
    # block by design — the rule binds annotated data locks only.
    # The file still annotates ANOTHER lock so the scan runs.
    _write(tmp_path, "mod.py", """\
        import threading

        class Rep:
            def __init__(self):
                self._lock = threading.Lock()
                self._doc = {}  # guarded-by: self._lock
                self._io = threading.Lock()

            def write(self, doc):
                with self._io:
                    with open("f", "w") as f:
                        f.write(str(doc))
        """)
    assert not [f for f in _lint(tmp_path) if f.rule == "VC205"]


def test_vc205_timeoutless_wait_and_suppression(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._evt = threading.Event()
                self._n = 0  # guarded-by: self._lock

            def bad(self):
                with self._lock:
                    self._evt.wait()

            def bounded(self):
                with self._lock:
                    self._evt.wait(0.1)

            def justified(self):
                with self._lock:
                    # lint: disable=VC205 test fixture: the waiter is
                    # the only other thread and never takes this lock
                    self._evt.wait()
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VC205"]
    assert len(found) == 1 and found[0].symbol == "Box.bad"


def test_status_reporter_io_stays_outside_data_lock():
    """Regression for the live VC205 fix: StatusReporter must never
    hold `_lock` across the status.json write (the engine scheduler
    tick calls update() synchronously)."""
    path = os.path.join(REPO, "veles_tpu", "runtime", "status.py")
    found = analyze_files(iter_python_files([path]))
    assert not [f for f in found if f.rule == "VC205"], found


# -- VA002: stale baseline entries + pruning ---------------------------------

def test_va002_for_baseline_entry_of_deleted_file(tmp_path):
    mod = _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    bp = str(tmp_path / "bl.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    write_baseline(bp, r1["all"])
    mod.unlink()
    _write(tmp_path, "other.py", "x = 1\n")   # keep the scan non-empty
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r2["findings"]) == ["VA002"]
    assert "file is gone" in r2["findings"][0].message
    assert r2["findings"][0].severity == "warning"


def test_va002_suppression_impossible_and_never_baselined(tmp_path):
    # VA002 points at the baseline's own debt: writing it into the
    # baseline must not hide it
    mod = _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    bp = str(tmp_path / "bl.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    write_baseline(bp, r1["all"])
    mod.write_text("x = 1\n")       # finding fixed, entry now stale
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r2["findings"]) == ["VA002"]
    write_baseline(bp, r2["all"])   # try to baseline the staleness
    r3 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r3["findings"]) == []   # rewrite pruned the entry


def test_write_baseline_prunes_deleted_files(tmp_path, capsys):
    mod = _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    _write(tmp_path, "keeper.py", "x = 1\n")
    bp = str(tmp_path / "bl.json")
    rc = lint_main([str(tmp_path), "--baseline", bp, "--write-baseline"])
    capsys.readouterr()
    assert rc == 0
    entries = json.load(open(bp))["findings"]
    assert len(entries) == 1
    mod.unlink()
    rc = lint_main([str(tmp_path), "--baseline", bp, "--write-baseline"])
    out = capsys.readouterr().out
    assert rc == 0 and "pruned 1" in out
    assert json.load(open(bp))["findings"] == []


# -- CLI: --changed + JSON schema -------------------------------------------

def _git(cwd, *args):
    r = subprocess.run(["git", *args], cwd=str(cwd),
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (args, r.stderr)
    return r.stdout


def test_cli_changed_lints_only_git_diff(tmp_path, capsys):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "--allow-empty", "-q", "-m", "root")
    clean = _write(tmp_path, "clean.py", "x = 1\n")
    _git(tmp_path, "add", "clean.py")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-q", "-m", "clean file")
    # an UNTRACKED file with a violation: --changed must see it
    _write(tmp_path, "dirty.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        rc = lint_main(["--changed", "--baseline", "none", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["files"] == 1            # only dirty.py was parsed
        assert {f["rule"] for f in out["findings"]} == {"VT101"}
        # nothing changed -> clean exit, NOT the zero-files usage error
        os.remove("dirty.py")
        rc = lint_main(["--changed", "--baseline", "none"])
        text = capsys.readouterr().out
        assert rc == 0 and "no changed Python files" in text
    finally:
        os.chdir(cwd)


def test_changed_style_subset_scan_no_inventory_rules(tmp_path):
    """Regression (review finding): a --changed-style FILE-LIST scan
    that happens to include an __init__.py and a metric-registering
    file must not fire the whole-inventory rules — VM402 ("registered
    nowhere") and VK302/VK303 ("read/documented nowhere") need a
    package-directory scan to prove their claim."""
    _write(tmp_path, "__init__.py", "")
    met = _write(tmp_path, "met.py", """\
        def setup(reg):
            reg.counter("vt_x_total", "documented")
        """)
    cfg = _write(tmp_path, "config.py", """\
        root = None

        def _defaults():
            root.common.alpha = 1
        """)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `vt_x_total` | counter |\n"
        "| `vt_ghost_total` | counter | registered elsewhere |\n")
    # package-DIRECTORY scan: inventory rules on (ghost + dead key)
    r_dir = run_analysis([str(tmp_path)], baseline_path=None,
                         docs_dir=str(docs))
    assert "VM402" in _rules(r_dir["findings"])
    assert "VK302" in _rules(r_dir["findings"])
    # file-LIST scan of the same files: inventory rules off
    r_files = run_analysis(
        [str(tmp_path / "__init__.py"), str(met), str(cfg)],
        baseline_path=None, docs_dir=str(docs))
    rules = _rules(r_files["findings"])
    assert "VM402" not in rules and "VK302" not in rules \
        and "VK303" not in rules, rules


def test_vc205_blocking_inside_except_handler(tmp_path):
    # retry paths are where sleeps live; the walker must see them
    _write(tmp_path, "mod.py", """\
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def bad(self):
                with self._lock:
                    try:
                        self._n += 1
                    except ValueError:
                        time.sleep(1)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VC205"]
    assert len(found) == 1 and "time.sleep" in found[0].message


def test_vc205_keyword_args_are_not_an_exemption(tmp_path):
    # q.get(block=True) and evt.wait(timeout=None) block forever —
    # a keyword argument alone must not exempt the call
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = None  # guarded-by: self._lock
                self._evt = threading.Event()

            def bad_get(self):
                with self._lock:
                    return self._q.get(block=True)

            def bad_wait(self):
                with self._lock:
                    self._evt.wait(timeout=None)

            def ok_wait(self):
                with self._lock:
                    self._evt.wait(timeout=0.5)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VC205"]
    assert sorted(f.symbol for f in found) == ["Box.bad_get",
                                               "Box.bad_wait"]


def test_cli_changed_restricts_to_path_scope(tmp_path, capsys):
    """--changed intersects the changed set with the positional scope
    (when it exists) so the pre-commit hook can't fail on files the CI
    gate never lints."""
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "--allow-empty", "-q", "-m", "root")
    (tmp_path / "pkg").mkdir()
    _write(tmp_path, "pkg/__init__.py", "")
    _write(tmp_path, "outside.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    _write(tmp_path, "pkg/inside.py", "x = 1\n")
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        # scoped to pkg/: the outside.py violation is out of scope
        rc = lint_main(["pkg", "--changed", "--baseline", "none"])
        out = capsys.readouterr().out
        assert rc == 0, out
        # unscoped via a nonexistent anchor: everything changed lints
        rc = lint_main(["--changed", "--baseline", "none"])
        capsys.readouterr()
        assert rc == 1      # default anchor veles_tpu doesn't exist
    finally:
        os.chdir(cwd)


def test_cli_changed_json_empty_is_still_json(tmp_path, capsys):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "--allow-empty", "-q", "-m", "root")
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        rc = lint_main(["--changed", "--baseline", "none", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["schema_version"] == 1 and out["findings"] == []
        assert sorted(out["by_family"]) == [
            "VA0xx", "VC2xx", "VK3xx", "VM4xx", "VP6xx", "VR7xx",
            "VS5xx", "VT1xx"]
    finally:
        os.chdir(cwd)


def test_cli_json_schema_golden(tmp_path, capsys):
    """The --json contract CI dashboards chart: schema_version, the
    stable per-family count keys, and the per-finding field set."""
    _seeded_violations(tmp_path)
    rc = lint_main([str(tmp_path), "--baseline", "none", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["schema_version"] == 1
    assert sorted(out["by_family"]) == [
        "VA0xx", "VC2xx", "VK3xx", "VM4xx", "VP6xx", "VR7xx", "VS5xx",
        "VT1xx"]
    assert out["by_family"]["VT1xx"] == 1
    assert out["by_family"]["VC2xx"] >= 1
    assert out["by_family"]["VK3xx"] >= 1
    assert out["by_family"]["VS5xx"] == 0
    assert sum(out["by_family"].values()) == len(out["findings"])
    for f in out["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "col",
                          "symbol", "message", "hint", "snippet",
                          "fingerprint"}
    assert set(out) == {"schema_version", "findings", "by_family",
                        "accepted", "files", "baseline"}


def test_pre_commit_config_runs_the_gate():
    import re as _re
    cfg = open(os.path.join(REPO, ".pre-commit-config.yaml")).read()
    assert "veles_tpu.analysis" in cfg and "--changed" in cfg
    # the hook id is the one documented in docs/analysis.md
    assert _re.search(r"^\s*-?\s*id:\s*veles-tpu-lint\s*$", cfg, _re.M)


@pytest.mark.slow  # cold+warm full-gate wall-budget probe (~21s); the gate
# itself still runs tier-1 via the analysis marker's subprocess test
def test_full_package_run_under_budget(tmp_path):
    """New rule families must not quietly make the tier-1 gate slow.
    At whole-package scope with the cross-module graph the budget is
    ≤ 5 s COLD (no summary cache) and ≤ 2 s WARM (memo served from
    .veles-lint-cache.json) on an idle machine — but wall-clock
    absolutes flake under CPU contention (a loaded CI box slows the
    analyzer and everything else alike, and this test used to be the
    suite's one flake class).  So the bounds SCALE: a single-file
    parse of the package's largest module, measured best-of-3 right
    here under whatever load exists right now, is the yardstick — the
    whole cold run costs ~40 parse-equivalents, so 80x the measured
    parse is a ~2x-headroom budget that widens exactly as much as
    contention slows the probe.  The idle-machine floors keep the
    contract meaningful on fast hardware.  Best of two per leg damps
    scheduler noise — the budget is the contract, the retry is not."""
    import time
    pkg = os.path.join(REPO, "veles_tpu")
    docs = os.path.join(REPO, "docs")
    probe = os.path.join(pkg, "runtime", "engine.py")
    baseline = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        parse_file(probe, "runtime/engine.py")
        baseline = min(baseline, time.perf_counter() - t0)
    cold_budget = max(5.0, 80.0 * baseline)
    warm_budget = max(2.0, 20.0 * baseline)

    cold = float("inf")
    for i in range(2):
        cache = str(tmp_path / f"cold{i}.json")   # fresh: a cold run
        t0 = time.perf_counter()
        report = run_analysis([pkg], baseline_path=None, docs_dir=docs,
                              cache_path=cache)
        cold = min(cold, time.perf_counter() - t0)
    assert report["files"] > 90
    assert cold < cold_budget, \
        f"cold full-package analysis took {cold:.2f}s " \
        f"(budget {cold_budget:.2f}s at parse baseline " \
        f"{baseline * 1e3:.0f}ms)"

    cache = str(tmp_path / "warm.json")
    run_analysis([pkg], baseline_path=None, docs_dir=docs,
                 cache_path=cache)
    warm = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        report = run_analysis([pkg], baseline_path=None, docs_dir=docs,
                              cache_path=cache)
        warm = min(warm, time.perf_counter() - t0)
    assert report["files"] > 90
    assert warm < warm_budget, \
        f"warm full-package analysis took {warm:.2f}s " \
        f"(budget {warm_budget:.2f}s at parse baseline " \
        f"{baseline * 1e3:.0f}ms)"


# -- CLI contract (acceptance criteria) -------------------------------------

def _seeded_violations(tmp_path):
    """One fixture dir violating all three rule families."""
    _write(tmp_path, "config.py", """\
        root = None

        def _defaults():
            root.common.alpha = 1
        """)
    _write(tmp_path, "bad.py", """\
        import threading

        import jax.numpy as jnp

        from config import root

        _lock = threading.Lock()
        _state = {}  # guarded-by: _lock


        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:                      # VT101
                return y
            return -y


        def poke():
            _state["k"] = root.common.get("typo_key", 0)  # VC201+VK301
        """)


def test_cli_exits_nonzero_on_seeded_violations(tmp_path, capsys):
    _seeded_violations(tmp_path)
    rc = lint_main([str(tmp_path), "--baseline", "none", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    rules = {f["rule"] for f in out["findings"]}
    # all three families fire
    assert {"VT101", "VC201", "VK301"} <= rules


def test_cli_text_output_and_write_baseline(tmp_path, capsys):
    _seeded_violations(tmp_path)
    bp = str(tmp_path / "bl.json")
    rc = lint_main([str(tmp_path), "--baseline", bp])
    text = capsys.readouterr().out
    assert rc == 1 and "VT101" in text and "error" in text

    rc = lint_main([str(tmp_path), "--baseline", bp,
                    "--write-baseline"])
    capsys.readouterr()
    assert rc == 0 and os.path.isfile(bp)
    rc = lint_main([str(tmp_path), "--baseline", bp])
    out = capsys.readouterr().out
    assert rc == 0 and "accepted by baseline" in out


# -- the gate: live package is clean, pure-AST, no heavy imports ------------

def test_cli_zero_files_is_a_usage_error(tmp_path, capsys):
    # a typo'd path / wrong cwd must not silently DISABLE the gate by
    # "cleanly" analyzing nothing
    rc = lint_main([str(tmp_path / "nope"), "--baseline", "none"])
    capsys.readouterr()
    assert rc == 2


def test_fingerprints_are_cwd_independent(tmp_path):
    # display paths anchor at the analyzed dir's parent, so baseline
    # fingerprints written from the repo root match a run from anywhere
    pkg = os.path.join(REPO, "veles_tpu")
    files = iter_python_files([pkg])
    rels = dict(files)
    assert all(r.startswith("veles_tpu" + os.sep) or
               r.startswith("veles_tpu/") for r in rels.values())
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        assert iter_python_files([pkg]) == files
    finally:
        os.chdir(cwd)


def test_package_zero_unbaselined_findings():
    """THE tier-1 gate: `python -m veles_tpu.analysis veles_tpu` exits
    0 against the checked-in baseline (zero unbaselined findings)."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "veles_tpu.analysis", "veles_tpu"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert "clean: 0 findings" in r.stdout


def test_analyzer_runs_without_importing_heavy_modules():
    """Pure-AST regression: linting the whole package must not import
    the modules it analyzes (runtime/units/ops/...) — the lazy package
    __init__ keeps `veles_tpu.analysis` a stdlib-only import, so the
    lint gate stays milliseconds-scale and jax-free."""
    code = textwrap.dedent("""\
        import sys
        from veles_tpu.analysis.cli import main
        rc = main(["veles_tpu"])
        heavy = [m for m in sys.modules
                 if m.startswith("veles_tpu.")
                 and any(seg in m for seg in (
                     "runtime", "units", "ops", "parallel", "models",
                     "loader", "export", "forge", "genetics"))]
        assert rc == 0, "lint gate failed"
        assert not heavy, f"analyzer imported heavy modules: {heavy}"
        """)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"


def test_registry_roots_exist():
    """Renaming a traced builder must not silently drop it from the
    analyzer's root set: every registry qualname resolves in its
    module."""
    pkg = os.path.join(REPO, "veles_tpu")
    for relmod, roots in TRACE_ROOTS.items():
        path = os.path.join(pkg, relmod)
        assert os.path.isfile(path), relmod
        pf = parse_file(path, relmod)
        for q in roots:
            assert q in pf.functions, (relmod, q)


def test_console_script_entry_point(tmp_path):
    """pyproject.toml packages the analyzer as a `veles-tpu-lint`
    console script (mirror of the PR 3 `veles-tpu` smoke test)."""
    import shutil

    ppt = open(os.path.join(REPO, "pyproject.toml")).read()
    m = re.search(r'^veles-tpu-lint\s*=\s*"([\w.]+):(\w+)"', ppt, re.M)
    assert m, "pyproject.toml must declare the veles-tpu-lint script"
    mod, func = m.groups()
    assert (mod, func) == ("veles_tpu.analysis.cli", "main")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-c",
         f"import {mod} as m, sys\n"
         f"sys.exit(m.{func}(['--help']))"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=300)
    assert r.returncode == 0, r.stderr
    assert "veles-tpu-lint" in r.stdout and "--baseline" in r.stdout
    exe = shutil.which("veles-tpu-lint")
    if exe:  # installed entry point present: must behave identically
        r = subprocess.run([exe, "--help"], capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0 and "--baseline" in r.stdout


# -- speculative-decode verify builder: registry + routing contract ----------

def test_verify_builder_registered_as_trace_root():
    """The speculative verify program's builder is a declared BUILDER
    root (docs/analysis.md registry-extension workflow): renaming it in
    runtime/engine.py without the registry would silently drop the
    VT1xx/VP6xx coverage this family provides."""
    from veles_tpu.analysis.registry import BUILDER
    entry = TRACE_ROOTS["runtime/engine.py"]
    assert entry.get("make_verify_fn") == BUILDER
    # and it must NOT be declared self-caching: the engine routes it
    # through StepCache (VP603's contract), not a private memo
    from veles_tpu.analysis.registry import SELF_CACHING_BUILDERS
    assert "make_verify_fn" not in SELF_CACHING_BUILDERS


def test_vp603_verify_builder_on_hot_path(tmp_path):
    """Positive fixture: calling the verify builder from a scheduler
    tick without StepCache routing is the lazy-recompile hazard VP603
    exists for — the live engine's `_compile_verify` routes through
    get_step, mirrored by the negative half."""
    _write(tmp_path, "mod.py", """\
        def make_verify_fn(plan, ctx, S, K):  # trace-root: builder
            def fn(x):
                return x
            return fn

        def tick(self, plan, ctx):  # host-loop-root:
            return make_verify_fn(plan, ctx, 4, 4)

        def tick_routed(self, plan, ctx, cache):  # host-loop-root:
            step, _, _ = cache.get_step(
                "verify", ("k", 4),
                lambda: (make_verify_fn(plan, ctx, 4, 4), None, None),
                ())
            return step
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP603"]
    assert found[0].symbol == "tick"
    assert "make_verify_fn" in found[0].message


def test_vp601_per_request_k_into_verify_builder(tmp_path):
    """Positive fixture: a per-request draft length flowing into the
    verify builder's static k slot would compile one program per
    distinct k — the exact hazard the ONE-static-k design forbids."""
    _write(tmp_path, "mod.py", """\
        def make_verify_fn(plan, S, K):  # trace-root: builder
            return K

        def serve(plan, requests):
            for req in requests:
                make_verify_fn(plan, 4, len(req.draft))
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP601"]


def test_engine_verify_call_sites_lint_clean():
    """Negative fixture on the LIVE code: runtime/engine.py (verify
    builder + scheduler interleave + drafter) and the touched
    generate.py/pallas path hold zero findings — the gate's exit-0 on
    the empty baseline covers the package, this pins the PR's files
    individually so a future regression names them."""
    pkg = os.path.join(REPO, "veles_tpu")
    files = [(os.path.join(pkg, rel), rel)
             for rel in ("runtime/engine.py", "runtime/generate.py",
                         "ops/pallas_kernels.py")]
    found = analyze_files(files, package_scan=False)
    assert [f for f in found if f.rule != "VM402"] == []


# -- megastep builder: registry + routing contract ---------------------------

def test_megastep_builder_registered_as_trace_root():
    """The fused-decode program's builder — the fourth program kind —
    is a declared BUILDER root exactly like the verify builder landed:
    renaming it in runtime/engine.py without the registry would
    silently drop its VT1xx/VP6xx coverage."""
    from veles_tpu.analysis.registry import BUILDER
    entry = TRACE_ROOTS["runtime/engine.py"]
    assert entry.get("make_megastep_fn") == BUILDER
    # routed through StepCache (VP603's contract), not a private memo
    from veles_tpu.analysis.registry import SELF_CACHING_BUILDERS
    assert "make_megastep_fn" not in SELF_CACHING_BUILDERS


def test_vp603_megastep_builder_on_hot_path(tmp_path):
    """Positive fixture: calling the megastep builder from a scheduler
    tick without StepCache routing is the lazy-recompile hazard VP603
    exists for — the live engine's `_compile_megastep` routes through
    get_step, mirrored by the negative half."""
    _write(tmp_path, "mod.py", """\
        def make_megastep_fn(plan, ctx, S, N):  # trace-root: builder
            def fn(x):
                return x
            return fn

        def tick(self, plan, ctx):  # host-loop-root:
            return make_megastep_fn(plan, ctx, 4, 8)

        def tick_routed(self, plan, ctx, cache):  # host-loop-root:
            step, _, _ = cache.get_step(
                "megastep", ("mega", 8),
                lambda: (make_megastep_fn(plan, ctx, 4, 8), None, None),
                ())
            return step
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP603"]
    assert found[0].symbol == "tick"
    assert "make_megastep_fn" in found[0].message


def test_vp601_per_call_n_into_megastep_builder(tmp_path):
    """Positive fixture: a per-call burst length flowing into the
    megastep builder's static N slot would compile one fused program
    per distinct N — the exact hazard the ONE-static-N design (config
    `serve.megastep`, sealed at export) forbids."""
    _write(tmp_path, "mod.py", """\
        def make_megastep_fn(plan, S, N):  # trace-root: builder
            return N

        def serve(plan, requests):
            for req in requests:
                make_megastep_fn(plan, 4, len(req.window))
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP601"]


# -- whole-package closure: the cross-module blind spot, provably closed -----
#
# Each pair seeds a violation SPLIT ACROSS TWO FIXTURE MODULES and
# asserts (a) the cross-module closure yields exactly one finding with
# the right file:line, and (b) `cross_module=False` — the legacy
# module-local analyzer — cannot see it.

def _lint_local(tmp_path, **kw):
    return analyze_files(iter_python_files([str(tmp_path)]),
                         cross_module=False, **kw)


def _line_of(tmp_path, name, needle):
    src = (tmp_path / name).read_text()
    for i, line in enumerate(src.splitlines(), 1):
        if needle in line:
            return i
    raise AssertionError(f"{needle!r} not in {name}")


def test_cross_module_vt103_through_import(tmp_path):
    _write(tmp_path, "a.py", """\
        from helper import stamp

        def step(x):  # trace-root: traced
            return x + stamp()
        """)
    _write(tmp_path, "helper.py", """\
        import time

        def stamp():
            return time.monotonic()
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT103"]
    assert found[0].path.endswith("helper.py")
    assert found[0].line == _line_of(tmp_path, "helper.py",
                                     "time.monotonic()")
    assert found[0].symbol == "stamp"
    # the module-local closure provably misses it
    assert _lint_local(tmp_path) == []


def test_cross_module_vc204_lock_cycle(tmp_path):
    _write(tmp_path, "a.py", """\
        import threading

        from b import grab_b

        _a = threading.Lock()

        def one():
            with _a:
                grab_b()

        def grab_a():
            with _a:
                pass
        """)
    _write(tmp_path, "b.py", """\
        import threading

        from a import grab_a

        _b = threading.Lock()

        def two():
            with _b:
                grab_a()

        def grab_b():
            with _b:
                pass
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC204"]
    f = found[0]
    assert "_a" in f.message and "_b" in f.message
    assert f.path.endswith("a.py")
    assert f.line == _line_of(tmp_path, "a.py", "grab_b()")
    assert _lint_local(tmp_path) == []


def test_cross_module_vc205_blocking_through_import(tmp_path):
    _write(tmp_path, "a.py", """\
        import threading

        from b import write_status

        class Eng:
            def __init__(self):
                self._lock = threading.Lock()
                self._doc = {}  # guarded-by: self._lock

            def tick(self):
                with self._lock:
                    write_status(self._doc)
        """)
    _write(tmp_path, "b.py", """\
        def write_status(doc):
            with open("s.json", "w") as f:
                f.write(str(doc))
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC205"]
    f = found[0]
    assert f.path.endswith("a.py") and f.symbol == "Eng.tick"
    assert f.line == _line_of(tmp_path, "a.py",
                              "write_status(self._doc)")
    assert "write_status" in f.message and "_lock" in f.message
    assert _lint_local(tmp_path) == []


def test_cross_module_vp603_builder_via_helper_module(tmp_path):
    _write(tmp_path, "a.py", """\
        from b import warm

        def tick(plan):  # host-loop-root:
            return warm(plan)
        """)
    _write(tmp_path, "b.py", """\
        def make_step(plan):  # trace-root: builder
            def fn(x):
                return x
            return fn

        def warm(plan):
            return make_step(plan)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP603"]
    f = found[0]
    assert f.path.endswith("b.py") and f.symbol == "warm"
    assert f.line == _line_of(tmp_path, "b.py", "return make_step(plan)")
    assert _lint_local(tmp_path) == []


def test_cross_module_vp603_through_method_override(tmp_path):
    """The ArtifactRunner shape from the live runtime: a host loop in
    the BASE class reaches a hook OVERRIDDEN in another module, whose
    override calls a builder outside StepCache — invisible to any
    per-module analysis because no single file contains both the loop
    and the unrouted call."""
    _write(tmp_path, "base.py", """\
        class Engine:
            def loop(self):  # host-loop-root:
                while True:
                    self._compile()

            def _compile(self):
                return None
        """)
    _write(tmp_path, "runner.py", """\
        from base import Engine

        def make_step(plan):  # trace-root: builder
            return plan

        class Runner(Engine):
            def _compile(self):
                return make_step(self.plan)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VP603"]
    f = found[0]
    assert f.path.endswith("runner.py")
    assert f.symbol == "Runner._compile"
    assert _lint_local(tmp_path) == []


def test_cross_module_vs502_scope_follows_imports(tmp_path):
    """VS502's blind spot runs the other way: module-local analysis
    cannot tell a helper legitimately reached from another module's
    shard_map root apart from a genuinely unscoped collective — it
    flags BOTH (forcing spurious `# shard-map-root:` markers).  The
    package closure distinguishes them: exactly one finding, on the
    stray."""
    _write(tmp_path, "a.py", """\
        from b import mix

        def body(x):  # shard-map-root: seq
            return mix(x)
        """)
    _write(tmp_path, "b.py", """\
        import jax.lax

        def mix(x):
            return jax.lax.psum(x, "seq")

        def stray(x):
            return jax.lax.psum(x, "seq")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS502"]
    f = found[0]
    assert f.path.endswith("b.py") and f.symbol == "stray"
    # module-local: both helpers flagged — the closure can't see that
    # `mix` runs inside a's shard_map scope
    local = _lint_local(tmp_path)
    assert _rules(local) == ["VS502", "VS502"]


def test_cross_module_vs501_env_through_import(tmp_path):
    """Axis-environment checking follows the call too: a helper
    reached from a ("seq",)-scoped root may not psum over an axis that
    scope does not bind."""
    _write(tmp_path, "mesh.py", """\
        import jax

        def make(devices):
            return jax.sharding.Mesh(devices, ("seq", "data"))
        """)
    _write(tmp_path, "a.py", """\
        from b import mix

        def body(x):  # shard-map-root: seq
            return mix(x)
        """)
    _write(tmp_path, "b.py", """\
        import jax.lax

        def mix(x):
            return jax.lax.psum(x, "data")
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VS501"]
    assert found[0].path.endswith("b.py")
    assert "does not bind" in found[0].message


# -- VR7xx: resource lifecycles ---------------------------------------------

def test_vr701_leak_on_error_path(tmp_path):
    """Acceptance seed: a page taken from the pool leaks on a raise
    before any release/transfer — exactly one finding, file:line."""
    _write(tmp_path, "mod.py", """\
        class Pool:
            def alloc(self):  # resource-acquire: pages
                return 1

            def free(self, h):  # resource-release: pages
                pass

        class Sched:
            def admit(self, pool, req):
                h = pool.alloc()
                if req is None:
                    raise ValueError("bad request")
                req.h = h
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VR701"]
    f = found[0]
    assert f.path.endswith("mod.py") and f.symbol == "Sched.admit"
    assert f.line == _line_of(tmp_path, "mod.py", "raise ValueError")
    assert "pages" in f.message


def test_vr701_clean_lifecycles(tmp_path):
    """try/finally release, ownership transfer before the raise, and a
    handler that reaches the release through another function are all
    legitimate lifecycles."""
    _write(tmp_path, "mod.py", """\
        class Pool:
            def alloc(self):  # resource-acquire: pages
                return 1

            def free(self, h):  # resource-release: pages
                pass

        class Sched:
            def finally_path(self, pool, req):
                h = pool.alloc()
                try:
                    if req is None:
                        raise ValueError("bad")
                    req.h = h
                finally:
                    pool.free(h)

            def transfer_first(self, pool, req):
                h = pool.alloc()
                req.h = h
                if req.bad:
                    raise ValueError("late")

            def handler_reaches_release(self, pool, req):
                h = pool.alloc()
                try:
                    if req is None:
                        raise ValueError("bad")
                except ValueError:
                    self._cleanup(pool, h)
                    raise

            def _cleanup(self, pool, h):
                pool.free(h)
        """)
    assert _lint(tmp_path) == []


def test_vr701_exit_root_must_reach_release(tmp_path):
    """The registry's exit-root contract: a file matching the declared
    module whose retire path no longer reaches any release function
    fires at the exit root's def line (the refactor-rot guard for
    _retire/_post_step/_fail_all in the live engine)."""
    _write(tmp_path, "runtime/engine.py", """\
        class DecodeEngine:
            def _reserve_pages(self, req):
                return 1

            def _alloc_page_locked(self):
                return 1

            def _release_slot_pages(self, slot):
                pass

            def _invalidate_prefix_cache(self):
                pass

            def _retire(self, slot):
                pass

            def _post_step(self, finished):
                self._release_slot_pages(0)

            def _fail_all(self, err):
                self._release_slot_pages(0)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VR701"]
    assert len(found) == 1
    f = found[0]
    assert f.symbol == "DecodeEngine._retire"
    assert f.line == _line_of(tmp_path, "runtime/engine.py",
                              "def _retire")
    assert "kv-pages" in f.message


def test_vr701_preempt_exit_root_declared(tmp_path):
    """The preemption requeue path is a declared kv-pages exit root
    (docs/serving.md "Overload survival"): a file matching the engine
    module whose ``_preempt`` retires-and-requeues a slot WITHOUT
    releasing its pages fires at the def line — the victim's pages
    must provably free (or transfer) before the winner reserves, or
    every preemption leaks a span."""
    _write(tmp_path, "runtime/engine.py", """\
        class DecodeEngine:
            def _reserve_pages(self, req):
                return 1

            def _alloc_page_locked(self):
                return 1

            def _release_slot_pages(self, slot):
                pass

            def _invalidate_prefix_cache(self):
                pass

            def _retire(self, slot):
                self._release_slot_pages(slot)

            def _post_step(self, finished):
                self._release_slot_pages(0)

            def _fail_all(self, err):
                self._release_slot_pages(0)

            def _preempt(self, slot):
                self._queue.appendleft(self._slot_req[slot])

            def _advance_prefills(self):
                self._release_slot_pages(0)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VR701"]
    assert len(found) == 1
    f = found[0]
    assert f.symbol == "DecodeEngine._preempt"
    assert f.line == _line_of(tmp_path, "runtime/engine.py",
                              "def _preempt")
    assert "kv-pages" in f.message


def test_vr701_job_slots_exit_root_declared(tmp_path):
    """The batch-lane ledger is registry-tracked: a file matching the
    jobs module whose ``cancel`` no longer sweeps the in-flight ledger
    (reaches no release) fires at the def line — a cancelled job would
    otherwise pin ``vt_job_prompts_inflight`` forever."""
    _write(tmp_path, "runtime/jobs.py", """\
        class JobManager:
            def _acquire_job_slot(self, key):
                self._inflight[key] = 1

            def _release_job_slot(self, key):
                self._release_job_slot_locked(key)

            def _release_job_slot_locked(self, key):
                self._inflight.pop(key, None)

            def cancel(self, job_id):
                return job_id

            def stop(self):
                self._release_job_slot(None)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VR701"]
    assert len(found) == 1
    f = found[0]
    assert f.symbol == "JobManager.cancel"
    assert f.line == _line_of(tmp_path, "runtime/jobs.py",
                              "def cancel")
    assert "job-slots" in f.message


def test_vr702_unjoined_thread(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        def spawn(work):
            t = threading.Thread(target=work)
            t.start()
            return t
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VR702"]
    assert found[0].line == _line_of(tmp_path, "mod.py",
                                     "threading.Thread(target=work)")
    assert found[0].symbol == "spawn"


def test_vr702_daemon_and_cross_module_join_are_clean(tmp_path):
    # the join lives in ANOTHER module (the deploy stop_watcher shape):
    # only the package-wide view can prove the thread is collected
    _write(tmp_path, "a.py", """\
        import threading

        class Svc:
            def start(self):
                self._worker = threading.Thread(target=self._run)
                self._worker.start()
                self._poll = threading.Thread(target=self._run,
                                              daemon=True)
                self._poll.start()
        """)
    _write(tmp_path, "b.py", """\
        def stop(svc):
            svc._worker.join(timeout=10)
        """)
    assert _lint(tmp_path) == []


def test_vr702_skipped_on_subset_scans(tmp_path):
    # "joined nowhere" is only provable against a whole package
    _write(tmp_path, "mod.py", """\
        import threading

        def spawn(work):
            t = threading.Thread(target=work)
            t.start()
        """)
    found = analyze_files(iter_python_files([str(tmp_path)]),
                          package_scan=False)
    assert _rules(found) == []


def test_vr703_unclosed_handle(tmp_path):
    _write(tmp_path, "mod.py", """\
        def leak(path):
            f = open(path)
            data = f.read()
            f.close()
            return data
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VR703"]
    assert found[0].line == _line_of(tmp_path, "mod.py", "open(path)")
    assert found[0].symbol == "leak"


def test_vr703_managed_handles_are_clean(tmp_path):
    _write(tmp_path, "mod.py", """\
        import socket

        class Hub:
            def __init__(self, path):
                self._fh = open(path, "a")

        def with_block(path):
            with open(path) as f:
                return f.read()

        def finally_close(path):
            f = open(path)
            try:
                return f.read()
            finally:
                f.close()

        def transfer(host, port):
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.connect((host, port))
            return sock
        """)
    assert _lint(tmp_path) == []


def test_vr704_durable_write_without_staging(tmp_path):
    _write(tmp_path, "mod.py", """\
        import json
        import os

        def save_manifest(path, doc):  # durable-write:
            with open(path, "w") as f:
                json.dump(doc, f)

        def save_safe(path, doc):  # durable-write:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VR704"]
    assert found[0].symbol == "save_manifest"
    assert found[0].line == _line_of(tmp_path, "mod.py",
                                     'open(path, "w")')


def test_resource_pairs_registry_honest():
    """The declared resource lifecycles stay real: every qualname
    resolves in its module, acquire/release functions actually touch
    the resource's backing fields, and every exit root reaches a
    release (the live gate would fire VR701 otherwise — this pins the
    declarations themselves).  Per resource, the fields its lifecycle
    provably manipulates: the kv-page pool's free list / refcounts,
    and the fleet router's per-replica pending-dispatch ledger."""
    import ast as _ast
    from veles_tpu.analysis.registry import RESOURCE_PAIRS
    pkg = os.path.join(REPO, "veles_tpu")
    backing_fields = {
        "kv-pages": ("_page_free", "_page_ref"),
        # the ledger dict, or the locked helper that owns its mutation
        # (the public release is a lock-taking delegate)
        "fleet-dispatch": ("_pending", "_end_dispatch_locked"),
        # the import lifecycle moves pages between the SAME pool
        # fields the kv-pages pair guards
        "kv-transfer": ("_page_free", "_page_ref"),
        # the job manager's in-flight dispatch ledger (batch lane) —
        # same delegate shape as fleet-dispatch: the public release
        # takes the lock and calls the locked mutator
        "job-slots": ("_inflight", "_release_job_slot_locked"),
        # the engine's open streaming-handle set (streaming serving)
        "stream-handles": ("_streams",),
        # the experiment manager's claimed-trial ledger — claim before
        # training, pop on durable commit or abort
        "experiment-trials": ("_claimed",),
    }
    assert set(RESOURCE_PAIRS) == set(backing_fields), \
        "new resource? declare its backing fields here too"
    for name, decl in RESOURCE_PAIRS.items():
        fields = backing_fields[name]
        for kind in ("acquire", "release", "exit_roots"):
            for relmod, quals in decl[kind].items():
                path = os.path.join(pkg, relmod)
                assert os.path.isfile(path), relmod
                pf = parse_file(path, relmod)
                for q in quals:
                    assert q in pf.functions, (relmod, q)
                    if kind in ("acquire", "release"):
                        seg = _ast.get_source_segment(
                            pf.source, pf.functions[q].node)
                        assert any(f in seg for f in fields), (name, q)


def test_fleet_host_loop_roots_resolve():
    """The fleet router's declared host loops (HOST_LOOP_ROOTS —
    scrape thread, dispatch path, rolling drain) resolve to real
    functions in runtime/fleet.py: a typo'd qualname would silently
    un-gate VP603 for the whole control plane, and the router is pure
    control plane — its files must also lint clean standalone."""
    from veles_tpu.analysis.registry import HOST_LOOP_ROOTS
    pkg = os.path.join(REPO, "veles_tpu")
    decl = HOST_LOOP_ROOTS["runtime/fleet.py"]
    pf = parse_file(os.path.join(pkg, "runtime", "fleet.py"),
                    "runtime/fleet.py")
    for q in decl:
        assert q in pf.functions, q
    files = [(os.path.join(pkg, rel), rel)
             for rel in ("runtime/fleet.py", "runtime/fleet_client.py")]
    found = analyze_files(files, package_scan=False)
    assert [f for f in found if f.rule != "VM402"] == [], found


# -- the summary cache -------------------------------------------------------

def test_cache_warm_run_skips_parsing(tmp_path, monkeypatch):
    """A warm unchanged re-run is served from the findings memo: the
    second run must not parse a single file (the ≤2s warm budget's
    mechanism, pinned behaviorally)."""
    _write(tmp_path, "pkg/__init__.py", "")
    _write(tmp_path, "pkg/mod.py", """\
        import time

        def step(x):  # trace-root: traced
            return x + time.monotonic()
        """)
    cache = str(tmp_path / "cache.json")
    r1 = run_analysis([str(tmp_path / "pkg")], baseline_path=None,
                      docs_dir=None, cache_path=cache)
    assert _rules(r1["all"]) == ["VT103"]
    assert os.path.isfile(cache)

    import veles_tpu.analysis.engine as eng

    def boom(*a, **kw):
        raise AssertionError("warm run parsed a file")

    monkeypatch.setattr(eng, "parse_file", boom)
    monkeypatch.setattr(eng, "ParsedFile", boom)
    r2 = run_analysis([str(tmp_path / "pkg")], baseline_path=None,
                      docs_dir=None, cache_path=cache)
    assert [f.to_dict() for f in r2["all"]] \
        == [f.to_dict() for f in r1["all"]]


def test_cache_edit_invalidates_only_that_file(tmp_path):
    """Summaries key on content hashes: editing b.py refreshes exactly
    its entry; a.py's summary rides through untouched (and the
    findings memo retires, so results stay correct)."""
    _write(tmp_path, "pkg/__init__.py", "")
    _write(tmp_path, "pkg/a.py", "A = 1\n")
    _write(tmp_path, "pkg/b.py", "B = 1\n")
    cache = str(tmp_path / "cache.json")
    run_analysis([str(tmp_path / "pkg")], baseline_path=None,
                 docs_dir=None, cache_path=cache)
    doc1 = json.load(open(cache))

    _write(tmp_path, "pkg/b.py", """\
        import time

        def step(x):  # trace-root: traced
            return x + time.monotonic()
        """)
    r2 = run_analysis([str(tmp_path / "pkg")], baseline_path=None,
                      docs_dir=None, cache_path=cache)
    assert _rules(r2["all"]) == ["VT103"]    # memo retired, not stale
    doc2 = json.load(open(cache))

    a_key = next(k for k in doc1["files"] if k.endswith("a.py"))
    b_key = next(k for k in doc1["files"] if k.endswith("b.py"))
    assert doc2["files"][a_key] == doc1["files"][a_key]
    assert doc2["files"][b_key]["hash"] != doc1["files"][b_key]["hash"]
    assert doc2["findings"]["context"] != doc1["findings"]["context"]


def test_subset_scan_closure_uses_package_summaries(tmp_path):
    """The --changed shape: rules run only on the changed file, but the
    cross-module closure still sees the whole package through
    summaries — a host loop in an UNCHANGED module makes the changed
    helper's unrouted builder call a finding."""
    _write(tmp_path, "pkg/__init__.py", "")
    _write(tmp_path, "pkg/a.py", """\
        from b import warm

        def tick(plan):  # host-loop-root:
            return warm(plan)
        """)
    _write(tmp_path, "pkg/b.py", """\
        def make_step(plan):  # trace-root: builder
            def fn(x):
                return x
            return fn

        def warm(plan):
            return make_step(plan)
        """)
    changed = [str(tmp_path / "pkg" / "b.py")]
    report = run_analysis(changed, baseline_path=None, docs_dir=None,
                          cache_path=str(tmp_path / "cache.json"),
                          scope_paths=[str(tmp_path / "pkg")])
    assert _rules(report["all"]) == ["VP603"]
    assert report["files"] == 1              # only b.py was analyzed
    # without the scope, the subset scan cannot see a.py's host loop
    narrow = run_analysis(changed, baseline_path=None, docs_dir=None,
                          cache_path=None)
    assert narrow["all"] == []


def test_comprehension_taint_follows_elements(tmp_path):
    """Iterating a tainted iterable yields tracer ELEMENTS (the
    comprehension targets join the env), while static projections of a
    traced pytree (`{a.shape[0] for a in leaves}`) stay static — both
    directions pinned after the review caught the element-passthrough
    false negative."""
    _write(tmp_path, "mod.py", """\
        import jax

        def bad(x):  # trace-root: traced
            vals = [v * 2 for v in x]
            if vals[0]:
                return vals
            return x

        def good(params):  # trace-root: traced
            shapes = {a.shape[0] for a in jax.tree.leaves(params)}
            if 3 in shapes:
                return params
            if len(shapes) > 1:
                return params
            return params
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT101"]
    assert found[0].symbol == "bad" and "vals[0]" in found[0].message


def test_cross_module_vc205_imported_module_lock(tmp_path):
    """Module-level locks canonicalize at their DEFINING module: a
    `from eng import _sched_lock` (or `eng._sched_lock`) held in
    another file merges with the guarded-by annotation in eng.py, so
    blocking under it cross-module still fires (review finding)."""
    _write(tmp_path, "eng.py", """\
        import threading

        _sched_lock = threading.Lock()
        _state = {}  # guarded-by: _sched_lock

        def poke(k):
            with _sched_lock:
                _state[k] = 1
        """)
    _write(tmp_path, "dep.py", """\
        import time

        import eng
        from eng import _sched_lock

        def slow_refresh(doc):
            with _sched_lock:
                time.sleep(1.0)

        def slow_refresh_attr(doc):
            with eng._sched_lock:
                time.sleep(1.0)
        """)
    found = [f for f in _lint(tmp_path) if f.rule == "VC205"]
    assert len(found) == 2
    assert all(f.path.endswith("dep.py") for f in found)
    assert {f.symbol for f in found} == {"slow_refresh",
                                        "slow_refresh_attr"}
    # the legacy module-local closure cannot connect the lock to its
    # annotation across the import
    assert not [f for f in _lint_local(tmp_path) if f.rule == "VC205"]


def test_vc204_distinct_object_locks_never_merge(tmp_path):
    """UNRESOLVABLE object-attribute locks (`a._lock` / `b._lock` on
    arbitrary objects) stay out of the ordering graph entirely: object
    lock identity is unknowable statically, so merging them (the old
    attr-name keying) or speculating distinct nodes both mint deadlock
    reports about locks that may never coexist (review finding)."""
    _write(tmp_path, "mod.py", """\
        def shuffle(a, b):
            with a._lock:
                with b._lock:
                    pass

        def shuffle_back(a, b):
            with b._lock:
                with a._lock:
                    pass
        """)
    assert [f for f in _lint(tmp_path) if f.rule == "VC204"] == []
