"""veles_tpu.analysis — the trace-discipline / host-concurrency /
config-drift static analyzer (docs/analysis.md).

Fixture snippets per rule family (positive + negative + suppression),
baseline semantics, the CLI contract, and — the CI gate itself — a
self-check that the live package holds ZERO unbaselined findings, run
pure-AST without importing any jax-heavy module.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from veles_tpu.analysis import (analyze_files, iter_python_files,
                                run_analysis)
from veles_tpu.analysis.baseline import write_baseline
from veles_tpu.analysis.cli import main as lint_main
from veles_tpu.analysis.pysrc import parse_file
from veles_tpu.analysis.registry import TRACE_ROOTS

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def _lint(tmp_path, **kw):
    return analyze_files(iter_python_files([str(tmp_path)]), **kw)


def _rules(findings):
    return sorted(f.rule for f in findings)


# -- VT1xx: trace safety ----------------------------------------------------

def test_vt101_tracer_branch_flagged(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT101"]
    assert "y > 0" in found[0].message
    assert found[0].symbol == "step"


def test_vt101_static_branches_not_flagged(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x, pages=None, *, greedy=True):  # trace-root: traced
            if pages is not None:      # None-check: static structure
                x = x + 1
            if greedy:                 # keyword-only knob: static
                return jnp.max(x)
            if x.ndim == 2:            # array metadata: static
                return x
            return jnp.sum(x)
        """)
    assert _lint(tmp_path) == []


def test_vt101_builder_params_are_static(tmp_path):
    # builder mode: the factory's own params are plans/config, not
    # tracers — but its nested def IS the traced program
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def make_step(page_size):  # trace-root: builder
            if page_size is None:
                page_size = 16

            def step(x):
                if jnp.sum(x) > 0:
                    return x
                return -x
            return step
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT101"]
    assert found[0].symbol == "make_step.step"


def test_vt102_host_coercions(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp
        import numpy as np

        def step(x):  # trace-root: traced
            a = float(jnp.sum(x))
            b = np.asarray(x * 2)
            c = x.sum().item()
            return a, b, c
        """)
    assert _rules(_lint(tmp_path)) == ["VT102", "VT102", "VT102"]


def test_vt103_host_effects_only_inside_traced_scope(tmp_path):
    _write(tmp_path, "mod.py", """\
        import random
        import time

        def step(x):  # trace-root: traced
            t = time.monotonic()
            r = random.random()
            return x + t + r

        def host_helper():
            return time.monotonic()    # not traced scope: fine
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT103", "VT103"]
    assert all(f.symbol == "step" for f in found)


def test_vt104_unordered_iteration(tmp_path):
    _write(tmp_path, "mod.py", """\
        def step(x):  # trace-root: traced
            acc = 0
            for k in {"b", "a"}:
                acc = acc + x
            for k in sorted({"b", "a"}):   # deterministic: fine
                acc = acc + x
            return acc
        """)
    assert _rules(_lint(tmp_path)) == ["VT104"]


def test_traced_scope_closes_over_local_calls(tmp_path):
    # a helper the traced root calls joins traced scope module-locally
    _write(tmp_path, "mod.py", """\
        import time

        def helper(n):
            return time.sleep(n)

        def step(x):  # trace-root: traced
            helper(1)
            return x
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VT103"]
    assert found[0].symbol == "helper"


# -- suppressions -----------------------------------------------------------

def test_suppression_with_reason(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            # lint: disable=VT101 trace-time structural check, honest
            if y > 0:
                return y
            return -y
        """)
    assert _lint(tmp_path) == []


def test_suppression_without_reason_is_va001(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:  # lint: disable=VT101
                return y
            return -y
        """)
    found = _lint(tmp_path)
    # the finding is suppressed, but the missing justification is
    # itself a finding
    assert _rules(found) == ["VA001"]


def test_suppression_only_covers_named_rule(tmp_path):
    _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:  # lint: disable=VT104 wrong rule named
                return y
            return -y
        """)
    assert _rules(_lint(tmp_path)) == ["VT101"]


# -- VC2xx: concurrency discipline ------------------------------------------

def test_vc201_guarded_field(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock

            def good(self, x):
                with self._lock:
                    self._items.append(x)

            def helper(self):  # requires-lock: self._lock
                return list(self._items)

            def bad(self):
                return len(self._items)
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "Box.bad"


def test_vc201_requires_lock_call_sites_checked(tmp_path):
    # annotating a method `# requires-lock:` moves the obligation to
    # its callers — it must not silently erase lock checking
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: self._lock

            def _bump(self):  # requires-lock: self._lock
                self._n += 1

            def good(self):
                with self._lock:
                    self._bump()

            def bad(self):
                self._bump()
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "Box.bad" and "_bump" in found[0].message


def test_vc201_not_shared_exemption(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock
                self._setup()

            def _setup(self):  # not-shared: called from __init__ only
                self._items.append(0)
        """)
    assert _lint(tmp_path) == []


def test_vc201_module_global(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        _lock = threading.Lock()
        _seen = set()  # guarded-by: _lock

        def good(k):
            with _lock:
                _seen.add(k)

        def bad(k):
            return k in _seen
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC201"]
    assert found[0].symbol == "bad"


def test_vc202_bare_acquire(tmp_path):
    _write(tmp_path, "mod.py", """\
        def risky(lock):
            lock.acquire()
            lock.release()

        def safe(lock):
            lock.acquire()
            try:
                pass
            finally:
                lock.release()
        """)
    found = _lint(tmp_path)
    assert _rules(found) == ["VC202"]
    assert found[0].symbol == "risky"


def test_vc203_unknown_lock_name(tmp_path):
    _write(tmp_path, "mod.py", """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lokc
        """)
    assert "VC203" in _rules(_lint(tmp_path))


# -- VK3xx: config drift ----------------------------------------------------

def _config_fixture(tmp_path):
    _write(tmp_path, "config.py", """\
        class _C:  # stand-in tree; the rule is pure AST
            pass

        root = _C()

        def _defaults():
            root.common.alpha = 1
            root.common.beta = 2
            root.common.serve.gamma = 3
        """)
    _write(tmp_path, "user.py", """\
        from config import root

        val = root.common.alpha
        missing = root.common.get("nope", 1)
        serve = root.common.serve
        g = serve.get("gamma", 3)
        """)


def test_vk301_undeclared_read(tmp_path):
    _config_fixture(tmp_path)
    found = [f for f in _lint(tmp_path) if f.rule == "VK301"]
    assert len(found) == 1
    assert "root.common.nope" in found[0].message
    assert found[0].path.endswith("user.py")


def test_vk302_dead_declaration(tmp_path):
    _config_fixture(tmp_path)
    dead = [f for f in _lint(tmp_path) if f.rule == "VK302"]
    assert ["root.common.beta" in f.message for f in dead] == [True]
    assert dead[0].path.endswith("config.py")


def test_vk303_undocumented_key(tmp_path):
    _config_fixture(tmp_path)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "configuration.md").write_text(
        "`root.common.alpha` and `root.common.serve.gamma` exist\n")
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VK303"]
    assert len(found) == 1 and "root.common.beta" in found[0].message


def test_vk_alias_get_counts_as_read(tmp_path):
    # serve = root.common.serve; serve.get("gamma") must NOT leave
    # gamma "dead" (the engine/deploy idiom)
    _config_fixture(tmp_path)
    assert not any("gamma" in f.message for f in _lint(tmp_path)
                   if f.rule == "VK302")


# -- VM4xx: metric-name drift ----------------------------------------------

def _metrics_fixture(tmp_path):
    # the __init__.py makes this a package-directory scan — the shape
    # VM402 requires (a subset scan cannot prove "registered nowhere")
    _write(tmp_path, "__init__.py", "")
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.counter("vt_good_total", "documented")
            reg.histogram("vt_lat_seconds", "documented histogram")
            reg.gauge("vt_undocumented_gauge", "nobody wrote me up")
            reg.counter("plain_counter", "not in the vt_ namespace")
        """)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `vt_good_total` | counter |\n"
        "`vt_lat_seconds` (derived: `vt_lat_seconds_bucket`,\n"
        "`vt_lat_seconds_sum`, `vt_lat_seconds_count`)\n"
        "| `vt_ghost_total` | counter | documented, never registered |\n")
    return docs


def test_vm401_registered_but_undocumented(tmp_path):
    docs = _metrics_fixture(tmp_path)
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VM401"]
    assert len(found) == 1
    assert "vt_undocumented_gauge" in found[0].message
    assert found[0].path.endswith("mod.py")
    assert found[0].severity == "error"


def test_vm402_documented_but_unregistered(tmp_path):
    docs = _metrics_fixture(tmp_path)
    found = [f for f in _lint(tmp_path, docs_dir=str(docs))
             if f.rule == "VM402"]
    # vt_ghost_total fires; the derived _bucket/_sum/_count series of
    # the registered histogram are exempt
    assert len(found) == 1
    assert "vt_ghost_total" in found[0].message


def test_vm402_skipped_on_subset_scans(tmp_path):
    """Linting one file (no package __init__.py in the scan) must not
    flag every metric registered in UNSCANNED modules as 'registered
    nowhere' — VM401 still fires per-file, VM402 needs the package."""
    docs = _metrics_fixture(tmp_path)
    mod = str(tmp_path / "mod.py")
    found = analyze_files(iter_python_files([mod]),
                          docs_dir=str(docs))
    rules = _rules(found)
    assert "VM402" not in rules          # subset scan: no VM402
    assert "VM401" in rules              # per-file check still on


def test_vm4xx_covers_perf_observability_names(tmp_path):
    """The deep-performance metric family (memory ledger, goodput/MFU,
    SLO burn, profiler) rides the same VM4xx contract as the serving
    metrics: registered+documented names pass, an undocumented
    registration of one fires VM401, a documented ghost fires VM402."""
    _write(tmp_path, "__init__.py", "")
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.gauge("vt_hbm_bytes_in_use", "documented")
            reg.gauge("vt_train_mfu", "documented")
            reg.gauge("vt_decode_mbu", "documented")
            reg.gauge("vt_slo_burn_rate", "documented",
                      labels=("slo",))
            reg.counter("vt_profile_captures_total", "documented")
            reg.gauge("vt_memory_headroom_slots", "nobody wrote me up")
        """)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| `vt_hbm_bytes_in_use` | gauge |\n"
        "| `vt_train_mfu` | gauge |\n"
        "| `vt_decode_mbu` | gauge |\n"
        "| `vt_slo_burn_rate` | gauge |\n"
        "| `vt_profile_captures_total` | counter |\n"
        "| `vt_hbm_bytes_limit` | gauge | documented, registered "
        "nowhere in this fixture |\n")
    found = _lint(tmp_path, docs_dir=str(docs))
    vm401 = [f for f in found if f.rule == "VM401"]
    vm402 = [f for f in found if f.rule == "VM402"]
    assert len(vm401) == 1
    assert "vt_memory_headroom_slots" in vm401[0].message
    assert len(vm402) == 1
    assert "vt_hbm_bytes_limit" in vm402[0].message


def test_perf_observability_modules_stay_host_side():
    """Guard: the memory poller / SLO ring / profiler layer is host
    code — no trace roots are declared in those modules, the analyzer
    finds nothing in them, and the engine's traced program builders
    never reference the observability layer (a thread or time.sleep
    leaking into a compiled program would be a silent perf bug the
    flat compile counters can't see)."""
    import ast
    for mod in ("runtime/memory.py", "runtime/slo.py",
                "runtime/profiler.py"):
        assert not TRACE_ROOTS.get(mod), mod
        path = os.path.join(REPO, "veles_tpu", mod)
        assert not analyze_files(iter_python_files([path])), mod
    # the traced-scope builders in engine/generate must not pull the
    # host observability layer into program scope
    banned = re.compile(
        r"\b(memory_monitor|slo_tracker|profiler|tree_bytes"
        r"|HistogramWindow)\b")
    for mod, roots in TRACE_ROOTS.items():
        if not roots:
            continue
        path = os.path.join(REPO, "veles_tpu", mod)
        tree = ast.parse(open(path).read())
        wanted = set()
        for q in roots:
            wanted.add(q.split(".")[-1])
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in wanted:
                src = ast.get_source_segment(open(path).read(), node)
                assert not banned.search(src or ""), (mod, node.name)


def test_vm4xx_noop_without_observability_md(tmp_path):
    _write(tmp_path, "mod.py", """\
        def setup(reg):
            reg.counter("vt_orphan_total", "no docs tree at all")
        """)
    assert not [f for f in _lint(tmp_path) if f.rule.startswith("VM")]
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "other.md").write_text("no observability file here\n")
    assert not [f for f in _lint(tmp_path, docs_dir=str(docs))
                if f.rule.startswith("VM")]


# -- baseline ---------------------------------------------------------------

def test_baseline_accepts_then_goes_stale_on_edit(tmp_path):
    mod = _write(tmp_path, "mod.py", """\
        import jax.numpy as jnp

        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """)
    bp = str(tmp_path / "baseline.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r1["findings"]) == ["VT101"]

    write_baseline(bp, r1["all"])
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert r2["findings"] == [] and _rules(r2["accepted"]) == ["VT101"]

    # editing the flagged line invalidates its fingerprint on purpose
    mod.write_text(mod.read_text().replace("if y > 0:", "if y > 1:"))
    r3 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r3["findings"]) == ["VT101"]


def test_va002_never_baselined(tmp_path):
    # a file that does not parse was never analyzed: no baseline may
    # green it (its fingerprint has no symbol/snippet to go stale on)
    _write(tmp_path, "broken.py", "def oops(:\n")
    bp = str(tmp_path / "bl.json")
    r1 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r1["findings"]) == ["VA002"]
    write_baseline(bp, r1["all"])
    r2 = run_analysis([str(tmp_path)], baseline_path=bp, docs_dir=None)
    assert _rules(r2["findings"]) == ["VA002"]     # still new


def test_config_alias_poisoned_by_unrelated_local(tmp_path):
    # `serve = {...}` in another function must not make its .get()
    # calls look like config reads (file-wide alias disqualification)
    _write(tmp_path, "config.py", """\
        root = None

        def _defaults():
            root.common.alpha = 1
        """)
    _write(tmp_path, "other.py", """\
        from config import root

        def a():
            serve = root.common.alpha
            return serve

        def b():
            serve = {"meta": 1}
            return serve.get("meta")
        """)
    assert not [f for f in _lint(tmp_path) if f.rule == "VK301"]


# -- CLI contract (acceptance criteria) -------------------------------------

def _seeded_violations(tmp_path):
    """One fixture dir violating all three rule families."""
    _write(tmp_path, "config.py", """\
        root = None

        def _defaults():
            root.common.alpha = 1
        """)
    _write(tmp_path, "bad.py", """\
        import threading

        import jax.numpy as jnp

        from config import root

        _lock = threading.Lock()
        _state = {}  # guarded-by: _lock


        def step(x):  # trace-root: traced
            y = jnp.sum(x)
            if y > 0:                      # VT101
                return y
            return -y


        def poke():
            _state["k"] = root.common.get("typo_key", 0)  # VC201+VK301
        """)


def test_cli_exits_nonzero_on_seeded_violations(tmp_path, capsys):
    _seeded_violations(tmp_path)
    rc = lint_main([str(tmp_path), "--baseline", "none", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    rules = {f["rule"] for f in out["findings"]}
    # all three families fire
    assert {"VT101", "VC201", "VK301"} <= rules


def test_cli_text_output_and_write_baseline(tmp_path, capsys):
    _seeded_violations(tmp_path)
    bp = str(tmp_path / "bl.json")
    rc = lint_main([str(tmp_path), "--baseline", bp])
    text = capsys.readouterr().out
    assert rc == 1 and "VT101" in text and "error" in text

    rc = lint_main([str(tmp_path), "--baseline", bp,
                    "--write-baseline"])
    capsys.readouterr()
    assert rc == 0 and os.path.isfile(bp)
    rc = lint_main([str(tmp_path), "--baseline", bp])
    out = capsys.readouterr().out
    assert rc == 0 and "accepted by baseline" in out


# -- the gate: live package is clean, pure-AST, no heavy imports ------------

def test_cli_zero_files_is_a_usage_error(tmp_path, capsys):
    # a typo'd path / wrong cwd must not silently DISABLE the gate by
    # "cleanly" analyzing nothing
    rc = lint_main([str(tmp_path / "nope"), "--baseline", "none"])
    capsys.readouterr()
    assert rc == 2


def test_fingerprints_are_cwd_independent(tmp_path):
    # display paths anchor at the analyzed dir's parent, so baseline
    # fingerprints written from the repo root match a run from anywhere
    pkg = os.path.join(REPO, "veles_tpu")
    files = iter_python_files([pkg])
    rels = dict(files)
    assert all(r.startswith("veles_tpu" + os.sep) or
               r.startswith("veles_tpu/") for r in rels.values())
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        assert iter_python_files([pkg]) == files
    finally:
        os.chdir(cwd)


def test_package_zero_unbaselined_findings():
    """THE tier-1 gate: `python -m veles_tpu.analysis veles_tpu` exits
    0 against the checked-in baseline (zero unbaselined findings)."""
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "veles_tpu.analysis", "veles_tpu"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert "clean: 0 findings" in r.stdout


def test_analyzer_runs_without_importing_heavy_modules():
    """Pure-AST regression: linting the whole package must not import
    the modules it analyzes (runtime/units/ops/...) — the lazy package
    __init__ keeps `veles_tpu.analysis` a stdlib-only import, so the
    lint gate stays milliseconds-scale and jax-free."""
    code = textwrap.dedent("""\
        import sys
        from veles_tpu.analysis.cli import main
        rc = main(["veles_tpu"])
        heavy = [m for m in sys.modules
                 if m.startswith("veles_tpu.")
                 and any(seg in m for seg in (
                     "runtime", "units", "ops", "parallel", "models",
                     "loader", "export", "forge", "genetics"))]
        assert rc == 0, "lint gate failed"
        assert not heavy, f"analyzer imported heavy modules: {heavy}"
        """)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"


def test_registry_roots_exist():
    """Renaming a traced builder must not silently drop it from the
    analyzer's root set: every registry qualname resolves in its
    module."""
    pkg = os.path.join(REPO, "veles_tpu")
    for relmod, roots in TRACE_ROOTS.items():
        path = os.path.join(pkg, relmod)
        assert os.path.isfile(path), relmod
        pf = parse_file(path, relmod)
        for q in roots:
            assert q in pf.functions, (relmod, q)


def test_console_script_entry_point(tmp_path):
    """pyproject.toml packages the analyzer as a `veles-tpu-lint`
    console script (mirror of the PR 3 `veles-tpu` smoke test)."""
    import shutil

    ppt = open(os.path.join(REPO, "pyproject.toml")).read()
    m = re.search(r'^veles-tpu-lint\s*=\s*"([\w.]+):(\w+)"', ppt, re.M)
    assert m, "pyproject.toml must declare the veles-tpu-lint script"
    mod, func = m.groups()
    assert (mod, func) == ("veles_tpu.analysis.cli", "main")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-c",
         f"import {mod} as m, sys\n"
         f"sys.exit(m.{func}(['--help']))"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=300)
    assert r.returncode == 0, r.stderr
    assert "veles-tpu-lint" in r.stdout and "--baseline" in r.stdout
    exe = shutil.which("veles-tpu-lint")
    if exe:  # installed entry point present: must behave identically
        r = subprocess.run([exe, "--help"], capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0 and "--baseline" in r.stdout
