"""Publishing subsystem + interactive API (reference: veles/publishing/,
veles/__init__.py callable module, veles/interaction.py Shell)."""

import json
import os

import numpy as np
import pytest

import veles_tpu as vt
from veles_tpu.interaction import Shell
from veles_tpu.loader import TRAIN, VALID, ArrayLoader
from veles_tpu.plotting import MetricsRecorder
from veles_tpu.publishing import (ConfluenceBackend, HtmlBackend,
                                  MarkdownBackend, PdfBackend, Publisher)


@pytest.fixture
def trained(rng):
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    loader = ArrayLoader({TRAIN: x, VALID: x}, {TRAIN: y, VALID: y},
                         minibatch_size=16)
    wf = vt.Workflow("pub_wf")
    wf.add(vt.units.All2AllTanh(8, name="fc1"))
    wf.add(vt.units.All2AllSoftmax(2, name="out", inputs=("fc1",)))
    wf.add(vt.units.EvaluatorSoftmax(
        name="ev", inputs=("out", "@labels", "@mask")))
    rec = MetricsRecorder("pub")
    tr = vt.Trainer(wf, loader, vt.optimizers.SGD(0.2),
                    vt.Decision(max_epochs=3), recorder=rec)
    tr.initialize(seed=1)
    tr.run()
    return tr, rec


def test_markdown_and_html_report(trained, tmp_path):
    tr, rec = trained
    pub = Publisher("Test run", "unit-test report",
                    backends=[MarkdownBackend(str(tmp_path)),
                              HtmlBackend(str(tmp_path))])
    pub.gather(trainer=tr, recorder=rec, config=vt.root)
    paths = pub.publish()
    md = open(paths[0]).read()
    assert "# Test run" in md
    assert "best_value" in md
    assert "fc1 → out → ev" in md
    assert "valid_error_pct" in md  # sparkline section
    html_doc = open(paths[1]).read()
    assert "<h1>Test run</h1>" in html_doc
    assert "fc1" in html_doc


def test_pdf_report_valid_structure(trained, tmp_path):
    tr, rec = trained
    pub = Publisher("PDF run", backends=[PdfBackend(str(tmp_path))])
    pub.gather(trainer=tr, recorder=rec)
    (path,) = pub.publish()
    data = open(path, "rb").read()
    assert data.startswith(b"%PDF-1.4")
    assert data.rstrip().endswith(b"%%EOF")
    assert b"/Type /Catalog" in data and b"/Type /Page" in data
    # xref offsets must point at the right objects
    xref_at = int(data.rsplit(b"startxref", 1)[1].split()[0])
    assert data[xref_at:xref_at + 4] == b"xref"
    # first object offset parses and lands on "1 0 obj"
    first_off = int(data[xref_at:].split(b"\n")[3].split()[0])
    assert data[first_off:first_off + 7] == b"1 0 obj"


def test_pdf_escapes_and_paginates(tmp_path):
    from veles_tpu.publishing.publisher import Report
    r = Report(title="esc (test) \\ page",
               results={f"metric_{i}": float(i) for i in range(80)})
    path = PdfBackend(str(tmp_path)).render(r)
    data = open(path, "rb").read()
    assert data.count(b"/Type /Page ") >= 2  # paginated
    assert rb"esc \(test\) \\ page" in data


def test_confluence_gated(trained):
    tr, rec = trained
    pub = Publisher("Conf run", backends=[
        ConfluenceBackend("http://127.0.0.1:9", "SPACE", timeout=0.5)])
    pub.gather(trainer=tr, recorder=rec)
    with pytest.raises(IOError, match="Confluence"):
        pub.publish()


def test_callable_module(tmp_path):
    cfg = tmp_path / "cfg.py"
    cfg.write_text("""
import numpy as np
import veles_tpu as vt
from veles_tpu.loader import ArrayLoader, TRAIN, VALID

def create(root):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(48, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    loader = ArrayLoader({TRAIN: x, VALID: x}, {TRAIN: y, VALID: y},
                         minibatch_size=16)
    wf = vt.Workflow("callable_wf")
    wf.add(vt.units.All2AllTanh(6, name="fc1"))
    wf.add(vt.units.All2AllSoftmax(2, name="out", inputs=("fc1",)))
    wf.add(vt.units.EvaluatorSoftmax(name="ev",
                                     inputs=("out", "@labels", "@mask")))
    return vt.Trainer(wf, loader, vt.optimizers.SGD(0.2),
                      vt.Decision(max_epochs=2))
""")
    result_file = tmp_path / "res.json"
    # the package itself is callable, like the reference's veles(...)
    code = vt(str(cfg), result_file=str(result_file))
    assert code == 0
    results = json.loads(result_file.read_text())
    assert "best_value" in results


def test_shell_noninteractive_noop(trained):
    tr, _ = trained
    sh = Shell(tr, interval=1)
    # stdin is not a tty under pytest: must not hang, must not raise
    sh.record(1, error_pct=5.0)
    sh.interact()


def test_shell_chains_recorder(trained):
    tr, _ = trained
    rec = MetricsRecorder("chained")
    sh = Shell(tr, interval=0, chain=rec)
    sh.record(0, error_pct=4.2)
    sh.record(1, error_pct=3.1)
    assert rec.series["error_pct"] == [4.2, 3.1]
    sh.close()


def test_callable_module_false_kwargs(tmp_path):
    # False/None kwargs must be omitted, not serialized as "--flag False"
    from veles_tpu.interaction import run as vrun
    cfg = tmp_path / "c.json"
    cfg.write_text(json.dumps({"common": {"x": 1}}))
    code = vrun(str(cfg), dump_config=True, verbose=False, snapshot=None)
    assert code == 0


def test_shell_exposes_chained_series(trained):
    tr, _ = trained
    rec = MetricsRecorder("inner")
    sh = Shell(tr, chain=rec)
    sh.record(0, loss=1.0)
    assert sh.series == {"loss": [1.0]}  # Publisher.gather sees metrics
    assert Shell(tr).series is None
