"""Self-organizing (non-SGD) units: Kohonen SOM + RBM — the reference's
non-gradient training paths (docs manualrst_veles_algorithms.rst:61-114)."""

import jax
import jax.numpy as jnp
import numpy as np

import veles_tpu as vt
from veles_tpu.units import KohonenForward, RBM, Spec, Workflow


def test_som_quantization_error_decreases(rng):
    centers = rng.standard_normal((4, 8)) * 2
    lab = rng.integers(0, 4, 256)
    x = (centers[lab] + 0.1 * rng.standard_normal((256, 8))).astype(
        np.float32)

    wf = Workflow("som")
    som = wf.add(KohonenForward((6, 6), init_lr=0.5, decay_steps=200,
                                name="som"))
    wf.build({"@input": Spec((64, 8), jnp.float32)})
    ws = wf.init_state(jax.random.key(0))
    step = wf.make_train_step(vt.optimizers.SGD(0.0), donate=False)

    e0 = float(som.quantization_error(ws["state"]["som"], x))
    for ep in range(30):
        for i in range(0, 256, 64):
            ws, _ = step(ws, {"@input": jnp.asarray(x[i:i + 64])})
    e1 = float(som.quantization_error(ws["state"]["som"], x))
    assert e1 < e0 * 0.5, (e0, e1)


def test_som_winner_output_shape():
    wf = Workflow("som")
    wf.add(KohonenForward((4, 4), name="som"))
    wf.build({"@input": Spec((8, 5), jnp.float32)})
    ws = wf.init_state(jax.random.key(1))
    predict = wf.make_predict_step("som")
    y = predict(ws, {"@input": jnp.ones((8, 5))})
    assert y.shape == (8,) and y.dtype == jnp.int32
    assert int(y.max()) < 16


def test_rbm_reconstruction_improves(rng):
    # binary-ish patterns: two prototype vectors + noise
    protos = (rng.random((2, 16)) > 0.5).astype(np.float32)
    idx = rng.integers(0, 2, 512)
    x = np.clip(protos[idx] + 0.05 * rng.standard_normal((512, 16)), 0, 1
                ).astype(np.float32)

    wf = Workflow("rbm")
    rbm = wf.add(RBM(8, lr=0.1, name="rbm"))
    wf.build({"@input": Spec((64, 16), jnp.float32)})
    ws = wf.init_state(jax.random.key(0))
    step = wf.make_train_step(vt.optimizers.SGD(0.0), donate=False)

    e0 = float(rbm.reconstruction_error(ws["state"]["rbm"], x))
    for ep in range(20):
        for i in range(0, 512, 64):
            ws, _ = step(ws, {"@input": jnp.asarray(x[i:i + 64])})
    e1 = float(rbm.reconstruction_error(ws["state"]["rbm"], x))
    assert e1 < e0 * 0.7, (e0, e1)


def test_rbm_update_deterministic_given_key(rng):
    from veles_tpu.units.base import Context
    x = jnp.asarray(rng.random((16, 8)).astype(np.float32))
    rbm = RBM(4, name="rbm")
    _, st = rbm.init(jax.random.key(0), [Spec((16, 8), jnp.float32)])
    ctx = Context(train=True, key=jax.random.key(42))
    s1 = rbm.update_state({}, st, [x], ctx)
    s2 = rbm.update_state({}, st, [x], ctx)
    np.testing.assert_allclose(np.asarray(s1["w"]), np.asarray(s2["w"]))
