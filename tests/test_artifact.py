"""Compiled-artifact subsystem (export/compiled.py + runtime/artifact.py):
export -> load -> serve must be golden against the live engine AND the
C++ runtime, integrity failures must raise the snapshot corruption
error, version skew must fail with a re-export message, and the deploy
control plane must hot-swap artifact weights with flat compile
counters under concurrent load."""

import json
import os
import subprocess
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import veles_tpu as vt
from veles_tpu.export import export_compiled, load_package, manifest_summary
from veles_tpu.export.compiled import MANIFEST
from veles_tpu.models.standard import build_workflow
from veles_tpu.ops import optimizers as opt
from veles_tpu.runtime.artifact import (ArtifactError, ArtifactRunner,
                                        ArtifactVersionError,
                                        load_artifact_weights,
                                        load_forward)
from veles_tpu.runtime.deploy import DeployController
from veles_tpu.runtime.engine import DecodeEngine
from veles_tpu.runtime.generate import generate
from veles_tpu.runtime.snapshotter import (SnapshotCorruptError,
                                           sha256_files)

pytestmark = pytest.mark.artifact

V, T = 12, 6
SLOTS, L_MAX = 3, 48

#: The flagship LM shape: GQA + RoPE + window attention, layer_norm,
#: FFN, a second attention — the chain the C++ goldens already pin
#: (tests/test_serving.py::test_cpp_generate_matches_jax).
LAYERS = [
    {"type": "embedding", "vocab": V, "dim": 16, "name": "emb"},
    {"type": "attention", "n_heads": 4, "n_kv_heads": 2, "rope": True,
     "residual": True, "window": 5, "name": "a1"},
    {"type": "layer_norm", "name": "n1"},
    {"type": "ffn", "d_hidden": 32, "name": "f1"},
    {"type": "attention", "n_heads": 2, "rope": True,
     "residual": True, "name": "a2"},
    {"type": "seq_last", "name": "last"},
    {"type": "softmax", "output_size": V, "name": "out"},
]

SERVING_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "serving")


def _build_lm(seed=21):
    wf = build_workflow("art_lm", LAYERS)
    wf.build({"@input": vt.Spec((2, T), jnp.int32),
              "@labels": vt.Spec((2,), jnp.int32),
              "@mask": vt.Spec((2,), jnp.float32)})
    ws = wf.init_state(jax.random.key(seed), opt.SGD(0.01))
    return wf, ws


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """One export pays for the module: (wf, ws, artifact_dir,
    manifest)."""
    tmp = tmp_path_factory.mktemp("artifact")
    wf, ws = _build_lm()
    art = str(tmp / "art")
    man = export_compiled(wf, ws, art, slots=SLOTS, l_max=L_MAX,
                          eos_id=0)
    return wf, ws, art, man


@pytest.fixture(scope="module")
def runner(exported):
    wf, ws, art, man = exported
    r = ArtifactRunner(art, window_ms=0.0).start()
    yield r
    r.stop()


def test_manifest_records_the_sealed_program_set(exported):
    wf, ws, art, man = exported
    assert man["workflow_checksum"] == wf.checksum()
    assert man["slots"] == SLOTS and man["l_max"] == L_MAX
    assert man["vocab"] == V and man["eos_id"] == 0
    assert man["buckets"] == [16, 32, 48]
    progs = man["programs"]
    assert set(progs) == {"forward", "decode", "prefill"}
    assert sorted(progs["prefill"]) == ["16", "32", "48"]
    for rel, sha in [(progs["decode"]["file"],
                      progs["decode"]["sha256"])] + [
            (q["file"], q["sha256"]) for q in progs["prefill"].values()]:
        assert sha256_files([os.path.join(art, rel)]) == sha
    # the summary names every program file (the CLI's --compiled print)
    summ = manifest_summary(man)
    assert len(summ["programs"]) == 5
    assert summ["checksum"] == wf.checksum()[:12]


def test_roundtrip_greedy_golden_and_flat_counters(exported, runner, rng):
    """The acceptance core: greedy tokens through the deserialized
    StableHLO programs are bitwise the live ``generate()``'s, across
    mixed shapes, with ZERO compiles after boot."""
    wf, ws, art, man = exported
    boot_compiles = runner.stats()["compile"]["compiles"]
    # boot compiled the whole inventory: decode + every prefill +
    # forward, nothing else, no recompiles
    assert boot_compiles == 2 + len(man["buckets"])
    # one shape per prefill bucket (16/32/48) — every sealed program
    # gets a golden pass without paying a generate() scan compile per
    # extra shape
    for p, n in [(3, 5), (21, 4), (40, 6)]:
        prompt = rng.integers(0, V, (1, p)).astype(np.int32)
        ref = np.asarray(generate(wf, ws, prompt, n))
        got = runner.generate(prompt, n, timeout=180)
        np.testing.assert_array_equal(got, ref, err_msg=f"P={p}")
    st = runner.stats()
    assert st["compile"]["compiles"] == boot_compiles, st["compile"]
    assert st["compile"]["recompiles"] == 0
    assert st["artifact"]["programs"] == 2 + len(man["buckets"])


def test_roundtrip_sampled_single_row_bitwise(exported, runner, rng):
    """Sampled decode (temperature + filters) through the artifact is
    bitwise the library path for single-row requests with the same
    key — the engine's own parity contract survives serialization."""
    wf, ws, art, man = exported
    prompt = rng.integers(0, V, (1, 5)).astype(np.int32)
    key = jax.random.key(7)
    ref = np.asarray(generate(wf, ws, prompt, 6, temperature=0.8,
                              top_k=5, top_p=0.9, key=key))
    got = runner.generate(prompt, 6, temperature=0.8, top_k=5,
                          top_p=0.9, key=key, timeout=180)
    np.testing.assert_array_equal(got, ref)


def test_forward_program_matches_predict(exported, runner, rng):
    wf, ws, art, man = exported
    x = rng.integers(0, V, (2, T)).astype(np.int32)
    ref = np.asarray(wf.make_predict_step("out")(
        ws, {"@input": jnp.asarray(x)}))
    got = np.asarray(runner.predict(runner.wstate,
                                    {"@input": jnp.asarray(x)}))
    np.testing.assert_array_equal(got, ref)


@pytest.fixture(scope="module")
def binary():
    r = subprocess.run(["make", "-s"], cwd=SERVING_DIR,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    return os.path.join(SERVING_DIR, "veles_serve")


def test_tri_runtime_greedy_golden(exported, runner, binary, tmp_path,
                                   rng):
    """The flagship acceptance bar: bitwise-identical greedy tokens
    through (a) live generate(), (b) the ArtifactRunner's deserialized
    programs, and (c) the C++ native runtime on the package export of
    the SAME weights."""
    wf, ws, art, man = exported
    N = 7
    prompt = rng.integers(0, V, (2, T)).astype(np.int32)
    ref = np.asarray(generate(wf, ws, prompt, N))                 # (a)

    got_art = runner.generate(prompt, N, timeout=180)             # (b)
    np.testing.assert_array_equal(got_art, ref)

    from veles_tpu.export import export_package
    pkg = str(tmp_path / "pkg")
    export_package(wf, ws, pkg,
                   input_spec={"shape": [2, T], "dtype": "float32"})
    np.save(tmp_path / "p.npy", prompt.astype(np.float32))
    r = subprocess.run(
        [binary, pkg, str(tmp_path / "p.npy"), str(tmp_path / "t.npy"),
         "--generate", str(N)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    got_cpp = np.load(tmp_path / "t.npy").astype(np.int32)        # (c)
    np.testing.assert_array_equal(got_cpp, ref)


# -- integrity / version discipline -----------------------------------------

def _copy_artifact(src, dst):
    import shutil
    shutil.copytree(src, dst)
    return str(dst)


def _flip_byte(path, offset=100):
    with open(path, "rb+") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def test_corrupt_tensors_raises_snapshot_corrupt(exported, tmp_path):
    wf, ws, art, man = exported
    bad = _copy_artifact(art, tmp_path / "bad_tensors")
    _flip_byte(os.path.join(bad, "tensors.npz"))
    with pytest.raises(SnapshotCorruptError, match="checksum mismatch"):
        ArtifactRunner(bad)
    # the weights-only loader (the deploy swap path) verifies too
    with pytest.raises(SnapshotCorruptError, match="checksum mismatch"):
        load_artifact_weights(bad)


def test_corrupt_program_raises_snapshot_corrupt(exported, tmp_path):
    wf, ws, art, man = exported
    bad = _copy_artifact(art, tmp_path / "bad_prog")
    _flip_byte(os.path.join(bad, "programs", "decode.bin"))
    with pytest.raises(SnapshotCorruptError, match="checksum mismatch"):
        ArtifactRunner(bad)


def test_damaged_manifest_raises_snapshot_corrupt(exported, tmp_path):
    """A parseable-but-damaged manifest (valid JSON, structural keys
    gone) is corruption too — the named error, not a bare KeyError from
    the first ``man["tensors"]``."""
    import shutil
    wf, ws, art, man = exported
    for damage in (lambda d: d.pop("tensors"),
                   lambda d: d["programs"]["decode"].pop("file"),
                   lambda d: d["programs"]["prefill"].update(x=3),
                   lambda d: d["programs"]["prefill"].update(
                       {"1x6": {"file": "programs/decode.bin"}}),
                   lambda d: d.pop("slots"),
                   lambda d: d.pop("input_spec")):
        bad = _copy_artifact(art, tmp_path / "bad_man")
        mp = os.path.join(bad, MANIFEST)
        doc = json.load(open(mp))
        damage(doc)
        json.dump(doc, open(mp, "w"))
        with pytest.raises(SnapshotCorruptError, match="damaged"):
            ArtifactRunner(bad)
        shutil.rmtree(bad)


def test_version_skew_fails_with_reexport_message(exported, tmp_path):
    """A serialized program from a newer jax.export calling convention
    must fail BEFORE deserializing, naming both versions and the fix
    (re-export) — not crash inside the flatbuffer parser."""
    wf, ws, art, man = exported
    bad = _copy_artifact(art, tmp_path / "bad_ver")
    mp = os.path.join(bad, MANIFEST)
    doc = json.load(open(mp))
    doc["programs"]["decode"]["calling_convention_version"] = 9999
    json.dump(doc, open(mp, "w"))
    with pytest.raises(ArtifactVersionError, match="re-export"):
        ArtifactRunner(bad)


def test_newer_format_version_refused(exported, tmp_path):
    """A manifest from a future format revision must refuse loudly at
    read time, not boot on a misread schema."""
    wf, ws, art, man = exported
    bad = _copy_artifact(art, tmp_path / "bad_fmt")
    mp = os.path.join(bad, MANIFEST)
    doc = json.load(open(mp))
    doc["format_version"] = 99
    json.dump(doc, open(mp, "w"))
    with pytest.raises(ArtifactVersionError, match="format version 99"):
        ArtifactRunner(bad)
    with pytest.raises(ArtifactVersionError, match="format version 99"):
        load_artifact_weights(bad)


def test_undeserializable_program_clear_error(exported, tmp_path):
    """Bytes that pass the checksum but aren't a replayable program
    (producer/consumer skew, not transit corruption) also land on the
    version error with the re-export hint."""
    wf, ws, art, man = exported
    bad = _copy_artifact(art, tmp_path / "bad_bytes")
    prog = os.path.join(bad, "programs", "decode.bin")
    with open(prog, "wb") as f:
        f.write(b"not a stablehlo program")
    mp = os.path.join(bad, MANIFEST)
    doc = json.load(open(mp))
    doc["programs"]["decode"]["sha256"] = sha256_files([prog])
    json.dump(doc, open(mp, "w"))
    with pytest.raises(ArtifactVersionError, match="re-export"):
        ArtifactRunner(bad)


def test_not_an_artifact_dir(tmp_path):
    with pytest.raises(ArtifactError, match="not a compiled artifact"):
        ArtifactRunner(str(tmp_path))


def test_out_of_vocab_eos_rejected_at_export(tmp_path):
    """A sealed eos_id becomes the serving default — exporting one
    outside the model's vocabulary would 400 every /generate of the
    artifact, so it must fail the EXPORT (and leave no artifact)."""
    wf, ws = _build_lm()
    out = str(tmp_path / "art")
    with pytest.raises(ValueError, match="outside the exported"):
        export_compiled(wf, ws, out, slots=2, l_max=16, eos_id=V)
    assert not os.path.exists(os.path.join(out, MANIFEST))
    assert not any(f.endswith(".tmp") for _, _, fs in os.walk(out)
                   for f in fs)

    # serving bounds eos by the INPUT embedding rows, so a head wider
    # than the embedding must not smuggle a default the server rejects
    wf2 = build_workflow("art_wide_head", [
        {"type": "embedding", "vocab": 8, "dim": 16, "name": "emb"},
        {"type": "attention", "n_heads": 2, "rope": True,
         "residual": True, "name": "a1"},
        {"type": "seq_last", "name": "last"},
        {"type": "softmax", "output_size": V, "name": "out"},
    ])
    wf2.build({"@input": vt.Spec((2, T), jnp.int32),
               "@labels": vt.Spec((2,), jnp.int32),
               "@mask": vt.Spec((2,), jnp.float32)})
    ws2 = wf2.init_state(jax.random.key(5), opt.SGD(0.01))
    with pytest.raises(ValueError, match=r"\[0, 8\)"):
        export_compiled(wf2, ws2, str(tmp_path / "art2"), slots=2,
                        l_max=16, eos_id=10)


def test_forward_only_artifact(tmp_path, rng):
    """A non-decodable chain exports forward-only: the manifest records
    why, ArtifactRunner refuses with a pointer to load_forward, and the
    forward leg golden-matches predict."""
    wf = build_workflow("art_fc", [
        {"type": "all2all_tanh", "output_size": 8, "name": "fc1"},
        {"type": "softmax", "output_size": 4, "name": "out"},
    ])
    wf.build({"@input": vt.Spec((4, 6), jnp.float32),
              "@labels": vt.Spec((4,), jnp.int32),
              "@mask": vt.Spec((4,), jnp.float32)})
    ws = wf.init_state(jax.random.key(5), opt.SGD(0.1))
    art = str(tmp_path / "fc_art")
    man = export_compiled(wf, ws, art)
    assert "decode" not in man["programs"]
    assert "decode_unsupported" in man
    with pytest.raises(ArtifactError, match="load_forward"):
        ArtifactRunner(art)
    predict, wstate, _ = load_forward(art)
    x = rng.standard_normal((4, 6)).astype(np.float32)
    ref = np.asarray(wf.make_predict_step("out")(
        ws, {"@input": jnp.asarray(x)}))
    np.testing.assert_array_equal(
        np.asarray(predict(wstate, {"@input": jnp.asarray(x)})), ref)


def test_cache_free_chain_roundtrip(tmp_path, rng):
    """A decodable chain with NO cached state (no attention/recurrent
    units): the manifest's cache rows are a structural marker only and
    the runner must rebuild an EMPTY cache tree, AOT-compile at boot,
    and serve golden tokens (regression: the empty-dict marker used to
    rebuild as a one-child tree and crash the scheduler on the first
    request)."""
    wf = build_workflow("art_nocache", [
        {"type": "embedding", "vocab": V, "dim": 8, "name": "emb"},
        {"type": "ffn", "d_hidden": 16, "name": "f1"},
        {"type": "seq_last", "name": "last"},
        {"type": "softmax", "output_size": V, "name": "out"},
    ])
    wf.build({"@input": vt.Spec((2, T), jnp.int32),
              "@labels": vt.Spec((2,), jnp.int32),
              "@mask": vt.Spec((2,), jnp.float32)})
    ws = wf.init_state(jax.random.key(11), opt.SGD(0.1))
    art = str(tmp_path / "nc")
    man = export_compiled(wf, ws, art, slots=2, l_max=16, bucket_min=8)
    assert "decode" in man["programs"]
    r = ArtifactRunner(art, window_ms=0.0).start()
    try:
        boot = r.stats()["compile"]["compiles"]
        prompt = rng.integers(0, V, (1, 4)).astype(np.int32)
        ref = np.asarray(generate(wf, ws, prompt, 4))
        np.testing.assert_array_equal(
            r.generate(prompt, 4, timeout=180), ref)
        assert r.stats()["compile"]["compiles"] == boot
    finally:
        r.stop()


# -- deploy control plane ----------------------------------------------------

def test_live_engine_hot_swaps_artifact_weights_flat_compiles(
        exported, tmp_path, rng):
    """DeployController moves a LIVE engine onto an artifact's weights
    under concurrent load: zero drops, compile counters flat, the
    registry entry carries kind='artifact', and post-swap greedy
    matches generate() on the artifact's weights."""
    wf, ws_a, art_a, _ = exported
    wf_b, ws_b = _build_lm(seed=77)            # same arch, new weights
    art_b = str(tmp_path / "art_b")
    export_compiled(wf_b, ws_b, art_b, slots=SLOTS, l_max=L_MAX)

    eng = DecodeEngine(wf, ws_a, slots=SLOTS, l_max=L_MAX,
                       window_ms=0.0).start()
    deploy = DeployController(engine=eng)
    shapes = [(3, 4), (7, 3), (11, 5)]
    prompts = [rng.integers(0, V, (1, p)).astype(np.int32)
               for p, _ in shapes]
    try:
        for pr, (_, n) in zip(prompts, shapes):  # warm every bucket
            eng.generate(pr, n, timeout=180)
        compiles = eng.stats()["compile"]["compiles"]
        errs, done = [], []
        stop = threading.Event()

        def worker(i):
            while not stop.is_set():
                try:
                    done.append(eng.generate(prompts[i], shapes[i][1],
                                             timeout=180).shape)
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))
                    return

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(shapes))]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 120
        while len(done) < 3:
            assert time.monotonic() < deadline, (done, errs)
            time.sleep(0.01)
        res = deploy.reload(f"artifact://{art_b}")
        assert res["compiles_during_swap"] == 0
        while len(done) < 8:  # keeps serving on the artifact weights
            assert time.monotonic() < deadline, (done, errs)
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=240)
        assert not errs, errs
        st = eng.stats()
        assert st["compile"]["compiles"] == compiles, st["compile"]
        entry = deploy.registry.active
        assert entry["kind"] == "artifact"
        assert entry["source"] == f"artifact://{art_b}"
        ref = np.asarray(generate(wf_b, ws_b, prompts[0], shapes[0][1]))
        np.testing.assert_array_equal(
            eng.generate(prompts[0], shapes[0][1], timeout=180), ref)
    finally:
        eng.stop()


def test_artifact_runner_hot_swap_under_load(exported, rng):
    """The sealed runner itself hot-swaps weights (same-architecture)
    with its deserialized programs untouched: counters flat across the
    swap under concurrent load, and the deploy boot source registers
    kind='artifact'."""
    wf, ws_a, art, _ = exported
    _, ws_b = _build_lm(seed=31)
    r = ArtifactRunner(art, window_ms=0.0).start()
    deploy = DeployController(engine=r,
                              boot_source=f"artifact://{art}")
    prompt = rng.integers(0, V, (1, 5)).astype(np.int32)
    try:
        assert deploy.registry.active["kind"] == "artifact"
        r.generate(prompt, 4, timeout=180)
        compiles = r.stats()["compile"]["compiles"]
        errs, done = [], []
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                try:
                    done.append(len(r.generate(prompt, 4, timeout=180)))
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))
                    return

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 120
        while len(done) < 2:
            assert time.monotonic() < deadline, (done, errs)
            time.sleep(0.01)
        r.swap_params(ws_b["params"])
        while len(done) < 6:
            assert time.monotonic() < deadline, (done, errs)
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=240)
        assert not errs, errs
        st = r.stats()
        assert st["compile"]["compiles"] == compiles, st["compile"]
        assert st["compile"]["recompiles"] == 0
        assert st["swaps"] == 1
        # the swapped weights serve bitwise like the library path
        ref = np.asarray(generate(wf, ws_b, prompt, 4))
        np.testing.assert_array_equal(
            r.generate(prompt, 4, timeout=180), ref)
    finally:
        r.stop()


def test_artifact_rejects_foreign_workflow(exported, tmp_path):
    """An artifact exported from a DIFFERENT architecture is refused by
    the checksum guard with the old version still serving."""
    wf, ws, art, _ = exported
    wf2 = build_workflow("other_lm", [
        {"type": "embedding", "vocab": V, "dim": 16, "name": "emb"},
        {"type": "seq_last", "name": "last"},
        {"type": "softmax", "output_size": V, "name": "out"},
    ])
    wf2.build({"@input": vt.Spec((2, T), jnp.int32),
               "@labels": vt.Spec((2,), jnp.int32),
               "@mask": vt.Spec((2,), jnp.float32)})
    ws2 = wf2.init_state(jax.random.key(1), opt.SGD(0.1))
    art2 = str(tmp_path / "foreign")
    # tiny geometry: the guard fires on the manifest checksum, long
    # before any program would load — no need to pay big exports here
    export_compiled(wf2, ws2, art2, slots=1, l_max=8, bucket_min=8)
    eng = DecodeEngine(wf, ws, slots=1, l_max=16, window_ms=0.0)
    deploy = DeployController(engine=eng)
    with pytest.raises(ValueError, match="different\\s+workflow"):
        deploy.reload(art2)
    assert deploy.registry.active_version == 1  # boot still active


def test_forge_stores_and_serves_artifact(exported, tmp_path, rng):
    """An artifact directory uploads to a ForgeStore like any package
    and hot-swaps back out via forge:// with kind='forge' — store/fetch
    parity for the compiled leg."""
    from veles_tpu.forge.store import ForgeStore
    wf, ws, art, man = exported
    store = ForgeStore(str(tmp_path / "store"))
    store.add(ForgeStore.pack_dir(art, {
        "name": "art_lm", "workflow": "art_lm",
        "configuration": "compiled-artifact"}))
    eng = DecodeEngine(wf, ws, slots=SLOTS, l_max=L_MAX,
                       window_ms=0.0).start()
    deploy = DeployController(engine=eng)
    try:
        res = deploy.reload(f"forge://{store.root_dir}/art_lm")
        assert res["active"]["kind"] == "forge"
        assert res["active"]["source"].endswith("@1")
        assert res["compiles_during_swap"] == 0
        prompt = rng.integers(0, V, (1, 4)).astype(np.int32)
        ref = np.asarray(generate(wf, ws, prompt, 4))
        np.testing.assert_array_equal(
            eng.generate(prompt, 4, timeout=180), ref)
    finally:
        eng.stop()


def test_rest_serving_without_workflow(exported, runner):
    """The REST layer serves a workflow-less (artifact) engine: decode
    works, vocab bounds come from the manifest, the manifest's sealed
    eos_id is the server default for requests that don't name one, and
    beam search is refused with a clear pointer instead of an
    AttributeError."""
    from veles_tpu.runtime.restful import RestfulServer
    wf, ws, art, man = exported
    srv = RestfulServer(
        runner.predict, runner.wstate, 2, (T,), workflow=None,
        engine=runner, input_dtype=np.int32,
        default_eos_id=man["eos_id"])
    try:
        out = srv.decode({"prompt": [[1, 2, 3]], "steps": 3})
        assert len(out["tokens"][0]) == 6
        # the sealed eos (0) governs default decode — parity with the
        # live path ASKED for that eos, not the eos-less one
        ref = np.asarray(generate(wf, ws,
                                  np.array([[1, 2, 3]], np.int32), 3,
                                  eos_id=man["eos_id"]))
        np.testing.assert_array_equal(np.asarray(out["tokens"]), ref)
        with pytest.raises(ValueError, match="in \\[0"):
            srv.decode({"prompt": [[V + 5]], "steps": 2})
        with pytest.raises(ValueError, match="live workflow"):
            srv.decode({"prompt": [[1]], "steps": 2, "beams": 3})
    finally:
        srv.httpd.server_close()


# -- speculative decode sealing (spec_decode + the verify program) ------------

def test_old_artifact_has_no_spec_and_loads_unchanged(exported, runner):
    """The module's default export predates/omits spec: spec_decode is
    null, no verify program ships, and the runner serves with spec off
    — old artifacts load unchanged."""
    _, _, _, man = exported
    assert man["spec_decode"] is None
    assert "verify" not in man["programs"]
    assert not runner.spec


def test_spec_requested_on_unsealed_artifact_is_refused(exported):
    """spec=True against an artifact that seals no verify program is a
    loud ArtifactError naming the re-export fix — the runner has no
    model code to trace one from."""
    _, _, art, _ = exported
    with pytest.raises(ArtifactError, match="verify"):
        ArtifactRunner(art, spec=True)


def test_spec_sealed_artifact_roundtrip_bitwise_flat_counters(
        tmp_path, rng):
    """export_compiled(spec=True) seals the verify program; the runner
    serves speculative decode by default (manifest k), bitwise the live
    generate() including a prefix-hit admission, counters flat after
    boot; spec=False still opts out."""
    wf, ws = _build_lm(seed=33)
    art = str(tmp_path / "spec_art")
    man = export_compiled(wf, ws, art, slots=2, l_max=32, spec=True,
                          spec_k=3)
    assert man["spec_decode"] == {"k": 3}
    assert "verify" in man["programs"]
    assert "programs/verify.bin" in manifest_summary(man)["programs"]
    r = ArtifactRunner(art, window_ms=0.0).start()
    try:
        assert r.spec and r.spec_k == 3
        boot = r.stats()["compile"]["compiles"]
        sysp = rng.integers(0, V, 16).astype(np.int32)   # 1 full page
        a = np.concatenate([sysp,
                            rng.integers(0, V, 3).astype(np.int32)])
        for pr, n in ((a[None], 10), (a[None], 10)):
            ref = np.asarray(generate(wf, ws, pr, n))
            np.testing.assert_array_equal(
                r.generate(pr, n, timeout=180), ref)
        st = r.stats()
        assert st["spec"]["verify_steps"] > 0
        assert st["pages"]["prefix_hit_pages"] >= 1
        assert st["compile"]["compiles"] == boot
        assert st["compile"]["recompiles"] == 0
        # prefill buckets + decode + verify (+ the batched forward)
        assert st["artifact"]["programs"] == (
            len(man["buckets"]) + 2
            + ("forward" in man["programs"]))
    finally:
        r.stop()
    assert not ArtifactRunner(art, spec=False).spec


def test_damaged_spec_decode_manifest_is_corruption(tmp_path):
    """A manifest claiming spec_decode without a sealed verify program
    (or without a static k) is parseable-but-damaged: the load answers
    SnapshotCorruptError (re-export), not a KeyError mid-boot."""
    wf, ws = _build_lm(seed=34)
    art = str(tmp_path / "dmg_art")
    export_compiled(wf, ws, art, slots=2, l_max=32, spec=True, spec_k=2)
    path = os.path.join(art, MANIFEST)
    man = json.load(open(path))
    man["spec_decode"] = {"k": "three"}          # no static int k
    json.dump(man, open(path, "w"))
    with pytest.raises(SnapshotCorruptError, match="spec_decode"):
        ArtifactRunner(art)
    man["spec_decode"] = {"k": 2}
    del man["programs"]["verify"]                # claim without blob
    json.dump(man, open(path, "w"))
    with pytest.raises(SnapshotCorruptError, match="spec_decode"):
        ArtifactRunner(art)
