"""Frontend form + sqlite snapshot target tests."""

import re
import urllib.error
import urllib.request

import numpy as np

import veles_tpu as vt
from veles_tpu.__main__ import build_parser
from veles_tpu.frontend import Frontend, form_to_argv, render_form


def test_render_form_covers_parser_options():
    html_text = render_form(build_parser())
    for field in ("config", "optimize", "mesh", "max_epochs", "dry_run"):
        assert f'name="{field}"' in html_text
    assert 'name="frontend"' not in html_text  # no recursive relaunch


def test_form_to_argv_roundtrip():
    parser = build_parser()
    fields = {
        "config": ["train.py"],
        "overrides": ["a.b=1 c.d=2"],
        "max_epochs": ["5"],
        "verbose": ["1"],
        "dry_run": ["build"],
    }
    argv = form_to_argv(parser, fields)
    args = parser.parse_args(argv)
    assert args.config == "train.py"
    assert args.overrides == ["a.b=1", "c.d=2"]
    assert args.max_epochs == 5
    assert args.verbose is True
    assert args.dry_run == "build"


def test_frontend_http_roundtrip():
    parser = build_parser()
    fe = Frontend(parser, port=0)
    try:
        url = f"http://127.0.0.1:{fe.port}/"
        page = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "compose a run" in page
        # The anti-CSRF token is embedded in the form; a legitimate
        # same-origin submit echoes it back.
        m = re.search(r'name="_token" value="([^"]+)"', page)
        assert m, "form must embed the CSRF token"
        data = f"_token={m.group(1)}&config=wf.py&max_epochs=3".encode()
        resp = urllib.request.urlopen(
            urllib.request.Request(url, data=data), timeout=10)
        assert b"Launched" in resp.read()
        argv = fe.wait(10)
        assert argv == ["wf.py", "--max-epochs", "3"]
    finally:
        fe.close()


def test_frontend_rejects_cross_origin_post():
    """A drive-by cross-origin POST carries no token — must not launch."""
    fe = Frontend(build_parser(), port=0)
    try:
        url = f"http://127.0.0.1:{fe.port}/"
        req = urllib.request.Request(url, data=b"config=evil.py")
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("tokenless POST must be rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 403
        assert fe.wait(0.05) is None  # nothing launched
        # Wrong Host header (DNS-rebinding shape) is rejected too.
        req = urllib.request.Request(url, data=b"x=1",
                                     headers={"Host": "evil.example"})
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("foreign Host must be rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 403
    finally:
        fe.close()


def test_snapshotter_to_db_roundtrip(tmp_path):
    db = str(tmp_path / "snaps.sqlite")
    snap = vt.SnapshotterToDB("m", db)
    payload = {
        "wstate": {"params": {"fc": {"w": np.arange(6.0).reshape(2, 3)}},
                   "step": np.int64(7)},
        "decision": {"best_value": 1.5},
        "workflow_checksum": "abc",
    }
    uri = snap.save("ep0", payload)
    assert uri.startswith("sqlite://") and uri.endswith("#1")
    loaded = vt.Snapshotter.load(uri)
    np.testing.assert_array_equal(loaded["wstate"]["params"]["fc"]["w"],
                                  payload["wstate"]["params"]["fc"]["w"])
    assert loaded["decision"]["best_value"] == 1.5
    assert loaded["workflow_checksum"] == "abc"
    # latest-row URI (no fragment)
    snap.save("ep1", payload)
    latest = vt.Snapshotter.load(f"sqlite://{db}")
    assert latest["tag" if "tag" in latest else "workflow_checksum"]


def test_trainer_restores_from_db(tmp_path, rng):
    from veles_tpu.loader.base import TRAIN, VALID
    from veles_tpu.units import nn as U
    from veles_tpu.units.workflow import Workflow

    X = rng.standard_normal((128, 8)).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.int32)

    def build():
        loader = vt.ArrayLoader({TRAIN: X[:96], VALID: X[96:]},
                                {TRAIN: y[:96], VALID: y[96:]},
                                minibatch_size=32)
        wf = Workflow("db")
        wf.add(U.All2AllTanh(6, name="fc1"))
        wf.add(U.All2AllSoftmax(2, name="out", inputs=("fc1",)))
        wf.add(U.EvaluatorSoftmax(name="ev",
                                  inputs=("out", "@labels", "@mask")))
        return wf, loader

    wf, loader = build()
    snap = vt.SnapshotterToDB("db", str(tmp_path / "s.sqlite"), interval=1)
    t1 = vt.Trainer(wf, loader, vt.optimizers.SGD(0.1),
                    vt.Decision(max_epochs=2), snapshotter=snap)
    t1.initialize(seed=0)
    t1.run()
    assert snap.last_path.startswith("sqlite://")

    wf2, loader2 = build()
    t2 = vt.Trainer(wf2, loader2, vt.optimizers.SGD(0.1),
                    vt.Decision(max_epochs=4))
    t2.initialize(seed=1)
    t2.restore(snap.last_path)
    np.testing.assert_allclose(
        np.asarray(t2.wstate["params"]["fc1"]["w"]),
        np.asarray(t1.wstate["params"]["fc1"]["w"]), rtol=1e-6)


def test_form_config_path_with_spaces_preserved():
    parser = build_parser()
    argv = form_to_argv(parser, {"config": ["/data/my runs/train.py"],
                                 "overrides": ["a.b=1 c.d=2"]})
    args = parser.parse_args(argv)
    assert args.config == "/data/my runs/train.py"
    assert args.overrides == ["a.b=1", "c.d=2"]


def test_frontend_close_after_timeout_is_clean():
    fe = Frontend(build_parser(), port=0)
    assert fe.wait(0.05) is None
    fe.close()  # must not crash the serve thread
    assert not fe._thread.is_alive()


def test_db_best_fragment_and_hash_path(tmp_path):
    d = tmp_path / "odd#dir"
    d.mkdir()
    db = str(d / "s.sqlite")
    snap = vt.SnapshotterToDB("m", db)
    pay = {"wstate": {"w": np.ones(2)}, "tag": "a"}
    snap.save("ep0", pay)
    best_uri = snap.save("ep1", {"wstate": {"w": np.full(2, 2.0)}},
                         best=True)
    snap.save("ep2", {"wstate": {"w": np.full(2, 3.0)}})
    # exact row id with '#' inside the db path
    loaded = vt.Snapshotter.load(best_uri)
    np.testing.assert_array_equal(loaded["wstate"]["w"], [2.0, 2.0])
    # '#best' pseudo-fragment (the _best symlink analog)
    best = vt.Snapshotter.load(f"sqlite://{db}#best")
    np.testing.assert_array_equal(best["wstate"]["w"], [2.0, 2.0])
    # latest
    latest = vt.Snapshotter.load(f"sqlite://{db}#current")
    np.testing.assert_array_equal(latest["wstate"]["w"], [3.0, 3.0])
