"""Recurrent units: scan-cell math vs numpy reference, numeric gradients
(the reference validated gradient units against NumDiff numeric
differentiation — veles/numpy_ext.py, SURVEY.md §4), and end-to-end
sequence classification through the Workflow/Trainer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import veles_tpu as vt
from veles_tpu.loader import TRAIN, VALID, ArrayLoader
from veles_tpu.ops import recurrent as rec
from veles_tpu.units import GRU, LSTM, RNN


def test_rnn_scan_matches_reference(rng):
    T, B, F, H = 5, 3, 4, 6
    xs = rng.normal(size=(T, B, F)).astype(np.float32)
    w = rng.normal(scale=0.3, size=(F + H, H)).astype(np.float32)
    b = rng.normal(scale=0.1, size=(H,)).astype(np.float32)
    h0 = np.zeros((B, H), np.float32)
    ys, h_final = rec.rnn_scan(jnp.asarray(xs), jnp.asarray(h0),
                               jnp.asarray(w), jnp.asarray(b))
    ys_ref, h_ref = rec.rnn_reference(xs, h0, w, b)
    np.testing.assert_allclose(np.asarray(ys), ys_ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_final), h_ref, atol=1e-5)


@pytest.mark.parametrize("cell", ["rnn", "gru", "lstm"])
def test_numeric_gradient(cell, rng):
    """jax.grad of a scalar loss through the scan matches central
    finite differences (NumDiff pattern)."""
    T, B, F, H = 3, 2, 3, 4
    n_gates = {"rnn": 1, "gru": 3, "lstm": 4}[cell]
    xs = jnp.asarray(rng.normal(size=(T, B, F)).astype(np.float32))
    w = jnp.asarray(rng.normal(
        scale=0.4, size=(F + H, n_gates * H)).astype(np.float32))
    b = jnp.asarray(rng.normal(scale=0.1,
                               size=(n_gates * H,)).astype(np.float32))
    h0 = jnp.zeros((B, H), jnp.float32)
    c0 = jnp.zeros((B, H), jnp.float32)

    def loss(w):
        if cell == "rnn":
            ys, _ = rec.rnn_scan(xs, h0, w, b)
        elif cell == "gru":
            ys, _ = rec.gru_scan(xs, h0, w, b)
        else:
            ys, _ = rec.lstm_scan(xs, h0, c0, w, b)
        return jnp.sum(ys ** 2)

    g = np.asarray(jax.grad(loss)(w))
    eps = 1e-3
    w_np = np.asarray(w)
    for idx in [(0, 0), (F + H - 1, n_gates * H - 1), (2, 1)]:
        wp, wm = w_np.copy(), w_np.copy()
        wp[idx] += eps
        wm[idx] -= eps
        num = (float(loss(jnp.asarray(wp))) -
               float(loss(jnp.asarray(wm)))) / (2 * eps)
        assert abs(g[idx] - num) < 3e-2 * max(1.0, abs(num)), \
            (cell, idx, g[idx], num)


@pytest.mark.parametrize("unit_cls", [RNN, GRU, LSTM])
def test_unit_shapes(unit_cls, rng):
    B, T, F, H = 4, 7, 5, 8
    x = jnp.asarray(rng.normal(size=(B, T, F)).astype(np.float32))
    for return_sequences, want in [(True, (B, T, H)), (False, (B, H))]:
        u = unit_cls(H, return_sequences=return_sequences)
        spec = u.output_spec([vt.Spec((B, T, F), jnp.float32)])
        assert spec.shape == want
        params, state = u.init(jax.random.key(0),
                               [vt.Spec((B, T, F), jnp.float32)])
        y, _ = u.apply(params, state, [x], vt.units.Context(train=True))
        assert y.shape == want
        assert np.isfinite(np.asarray(y)).all()


def test_unit_rejects_2d_input():
    u = LSTM(4)
    with pytest.raises(ValueError, match="batch, time"):
        u.output_spec([vt.Spec((8, 16), jnp.float32)])


def test_lstm_bf16_compute_close_to_f32(rng):
    B, T, F, H = 4, 6, 8, 16
    x = jnp.asarray(rng.normal(size=(B, T, F)).astype(np.float32))
    u32 = LSTM(H, compute_dtype=None)
    u16 = LSTM(H, compute_dtype="bfloat16")
    params, state = u32.init(jax.random.key(1),
                             [vt.Spec((B, T, F), jnp.float32)])
    y32, _ = u32.apply(params, state, [x], vt.units.Context())
    y16, _ = u16.apply(params, state, [x], vt.units.Context())
    # carry stays f32; only gemm operands are bf16 -> small deviation
    assert float(jnp.max(jnp.abs(y32 - y16))) < 0.05


def _sequence_dataset(rng, n, T=12, F=6):
    """Class = whether the cumulative sum of feature 0 ends positive —
    requires integrating over time, so a pure feedforward on the last
    step cannot solve it."""
    x = rng.normal(size=(n, T, F)).astype(np.float32)
    y = (x[:, :, 0].sum(axis=1) > 0).astype(np.int32)
    return x, y


@pytest.mark.parametrize("unit_cls", [GRU, LSTM])
def test_sequence_classification_end_to_end(unit_cls, rng):
    xtr, ytr = _sequence_dataset(rng, 256)
    xva, yva = _sequence_dataset(rng, 128)
    loader = ArrayLoader({TRAIN: xtr, VALID: xva},
                         {TRAIN: ytr, VALID: yva}, minibatch_size=32)
    wf = vt.Workflow(f"seq_{unit_cls.__name__}")
    wf.add(unit_cls(16, return_sequences=False, name="rec"))
    wf.add(vt.units.All2AllSoftmax(2, name="out", inputs=("rec",)))
    wf.add(vt.units.EvaluatorSoftmax(
        name="ev", inputs=("out", "@labels", "@mask")))
    trainer = vt.Trainer(wf, loader,
                         vt.optimizers.AdaGrad(0.08),
                         vt.Decision(max_epochs=12))
    trainer.initialize(seed=11)
    results = trainer.run()
    assert results["best_value"] < 25.0, results  # chance = 50 %


def test_recurrent_layers_from_standard_config(rng):
    """rnn/gru/lstm are config-constructible through StandardWorkflow
    (the reference shipped its RNN/LSTM units outside the workflow
    factory and untested)."""
    import veles_tpu as vt
    from veles_tpu.models.standard import StandardWorkflow
    for kind in ("rnn", "gru", "lstm"):
        sw = StandardWorkflow({
            "name": f"{kind}_model",
            "layers": [
                {"type": kind, "hidden": 12, "name": "rec",
                 "return_sequences": False},
                {"type": "softmax", "output_size": 3, "name": "out"},
            ],
            "optimizer": "sgd",
            "optimizer_args": {"lr": 0.1},
        })
        wf = sw.workflow
        batch = {
            "@input": jnp.asarray(
                rng.standard_normal((4, 6, 8)), jnp.float32),
            "@labels": jnp.zeros((4,), jnp.int32),
            "@mask": jnp.ones((4,), jnp.float32)}
        wf.build({k: vt.Spec(v.shape, v.dtype) for k, v in batch.items()})
        ws = wf.init_state(jax.random.key(0), sw.optimizer)
        step = wf.make_train_step(sw.optimizer)
        ws, mets = step(ws, batch)
        assert np.isfinite(float(mets["loss"])), kind
