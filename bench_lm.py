#!/usr/bin/env python
"""LM-family benchmark: training throughput with MFU, and KV-cached
decode tokens/sec (round-2 verdict #6: add an LM training-throughput row
with MFU; #4: a tokens/sec number for the decode path).

Model: the induction-LM topology scaled to a real size — embedding ->
4x full transformer blocks (residual RoPE attention, layer_norm,
residual 4E FFN unit, layer_norm) -> per-position softmax head, bf16
compute. Prints one JSON line per metric.

Run on the TPU host: ``python bench_lm.py [--decode-only]``.
"""

import json
import sys
import time

import numpy as np

# v5e peak dense bf16 matmul throughput (public spec), for MFU
V5E_PEAK_TFLOPS = 197.0

B, T, E, LAYERS, HEADS, VOCAB = 16, 2048, 512, 4, 8, 1024
DECODE_B, DECODE_P, DECODE_N = 8, 512, 64


def build(wstate_seed=0):
    import jax
    import jax.numpy as jnp
    import veles_tpu as vt
    from veles_tpu.models.standard import StandardWorkflow

    layers = [{"type": "embedding", "vocab": VOCAB, "dim": E,
               "name": "emb"}]
    for i in range(LAYERS):
        # full transformer block: attention + FFN halves (an
        # attention-only stack would understate both FLOPs and MFU)
        layers += [
            {"type": "attention", "n_heads": HEADS, "rope": True,
             "residual": True, "name": f"attn{i}"},
            {"type": "layer_norm", "name": f"ln{i}a"},
            {"type": "ffn", "d_hidden": 4 * E, "name": f"ffn{i}"},
            {"type": "layer_norm", "name": f"ln{i}b"},
        ]
    layers += [{"type": "all2all", "output_size": VOCAB,
                "per_position": True, "name": "head"}]
    sw = StandardWorkflow({
        "name": "bench_lm", "layers": layers,
        "compute_dtype": "bfloat16",
        "optimizer": "adam", "optimizer_args": {"lr": 1e-3},
    })
    wf = sw.workflow
    specs = {"@input": vt.Spec((B, T), jnp.int32),
             "@labels": vt.Spec((B, T), jnp.int32),
             "@mask": vt.Spec((B,), jnp.float32)}
    wf.build(specs)
    ws = wf.init_state(jax.random.key(wstate_seed), sw.optimizer)
    return sw, wf, ws


def main():
    decode_only = "--decode-only" in sys.argv
    # --smoke: tiny-shape validation (CPU-runnable) so the recovery
    # queue never fires a bit-rotted harness at the real shapes
    smoke = "--smoke" in sys.argv
    global B, T, E, LAYERS, HEADS, VOCAB, DECODE_B, DECODE_P, DECODE_N
    if smoke:
        B, T, E, LAYERS, HEADS, VOCAB = 2, 64, 32, 2, 2, 64
        DECODE_B, DECODE_P, DECODE_N = 2, 16, 8
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)

    sw, wf, ws = build()

    if not decode_only:
        step = wf.make_train_step(sw.optimizer)
        batch = {
            "@input": jnp.asarray(
                rng.integers(0, VOCAB, (B, T)), jnp.int32),
            "@labels": jnp.asarray(
                rng.integers(0, VOCAB, (B, T)), jnp.int32),
            "@mask": jnp.ones((B,), jnp.float32),
        }
        cost = jax.jit(step).lower(ws, batch).compile().cost_analysis()
        flops_per_step = float(cost.get("flops", 0.0))
        for _ in range(3):
            ws, mets = step(ws, batch)
        float(mets["loss"])  # drain (block_until_ready unreliable on axon)
        iters = 2 if smoke else 20
        t0 = time.perf_counter()
        for _ in range(iters):
            ws, mets = step(ws, batch)
        final = float(mets["loss"])
        dt = (time.perf_counter() - t0) / iters
        tokens_s = B * T / dt
        mfu = (flops_per_step / dt) / (V5E_PEAK_TFLOPS * 1e12)
        print(json.dumps({
            "metric": "lm_train_tokens_per_sec_per_chip",
            "value": round(tokens_s, 1), "unit": "tokens/sec/chip",
            "batch": B, "seq_len": T, "d_model": E, "layers": LAYERS,
            "step_ms": round(dt * 1e3, 2),
            "flops_per_step": flops_per_step,
            "mfu_vs_v5e_peak": round(mfu, 4),
            "final_loss": round(final, 4), "device": str(dev),
        }))

    # -- decode: KV-cached greedy generation -------------------------------
    from veles_tpu.runtime.generate import generate
    prompt = rng.integers(0, VOCAB, (DECODE_B, DECODE_P)).astype(np.int32)
    out = generate(wf, ws, prompt, DECODE_N)   # compile + warm
    float(jnp.sum(out))                        # drain
    t0 = time.perf_counter()
    out = generate(wf, ws, prompt, DECODE_N)
    float(jnp.sum(out))
    dt = time.perf_counter() - t0
    n_pos = DECODE_P + DECODE_N - 1            # cached steps executed
    print(json.dumps({
        "metric": "lm_decode_tokens_per_sec",
        "value": round(DECODE_B * DECODE_N / dt, 1), "unit": "tokens/sec",
        "batch": DECODE_B, "prompt_len": DECODE_P,
        "new_tokens": DECODE_N, "d_model": E, "layers": LAYERS,
        "positions_per_sec": round(DECODE_B * n_pos / dt, 1),
        "note": "KV-cached greedy decode; value counts NEW tokens only "
                "but the wall time includes prefilling the prompt "
                "through the same cached step (positions_per_sec is the "
                "raw step rate)",
        "device": str(dev),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
